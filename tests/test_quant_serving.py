"""Quantized serving path (ISSUE 9): int8 weight-only executables, the
int8 paged KV pool with per-position-per-head scales, prefix reuse /
COW / preemption on quantized pages, the fleet's numeric-contract
plumbing, and the fused dequant kernels.

Quantization is a BUDGET, not exact parity: the int8 engine is compared
against the fp32 paged engine under a declared logit-error budget plus
greedy-token match — the same gate bench.py --serving enforces.
Everything here runs the lax fallbacks (tier-1, CPU); the Pallas
kernels validate in interpret mode in the slow class at the bottom.
"""
import numpy as np
import pytest

# headroom over the 4.3e-3 the bench measures on gpt_tiny; way below
# any greedy-decision flip observed on these models
LOGIT_BUDGET = 0.05


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                      num_heads=2, max_seq_len=64, dtype="float32",
                      use_flash=False, remat=False)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    return params, cfg


def _make_engine(tiny_model, **kw):
    from paddle_tpu.inference.serving import PagedServingEngine
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("page_size", 8)
    kw.setdefault("seq_buckets", (8, 16))
    kw.setdefault("batch_buckets", (1, 2))
    return PagedServingEngine(tiny_model, **kw)


def _trace(n=8, seed=3, vocab=256):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, vocab, rng.randint(3, 15)).astype(np.int32),
             int(rng.randint(3, 8))) for _ in range(n)]


# --------------------------------------------------------------------------
# weight quantization units
# --------------------------------------------------------------------------

class TestQuantizeParams:
    def test_int8_leaves_and_reconstruction(self, tiny_model):
        import jax.numpy as jnp
        from paddle_tpu.models import gpt as G
        params, cfg = tiny_model
        qp = G.quantize_params(params, "int8")
        for name in ("qkv_w", "proj_w", "fc1_w", "fc2_w"):
            leaf = qp["blocks"][name]
            assert leaf["qw"].dtype == jnp.int8
            assert leaf["scale"].dtype == jnp.float32
            # per-output-channel: the contraction axis is size 1
            assert leaf["scale"].shape[1] == 1
            w = np.asarray(params["blocks"][name], np.float32)
            back = (np.asarray(leaf["qw"], np.float32)
                    * np.asarray(leaf["scale"]))
            # absmax int8 rounding: error bounded by scale/2 per entry
            bound = np.asarray(leaf["scale"]) / 2 + 1e-8
            assert (np.abs(w - back) <= bound).all(), name
        # untouched leaves stay untouched
        assert qp["wte"] is params["wte"]
        assert qp["blocks"]["qkv_b"] is params["blocks"]["qkv_b"]

    def test_dynamic_mode_marks_leaves(self, tiny_model):
        from paddle_tpu.models import gpt as G
        qp = G.quantize_params(tiny_model[0], "int8_dynamic")
        assert "qw_dyn" in qp["blocks"]["fc1_w"]
        assert "qw" not in qp["blocks"]["fc1_w"]

    def test_unknown_mode_raises(self, tiny_model):
        from paddle_tpu.models import gpt as G
        with pytest.raises(ValueError, match="quant mode"):
            G.quantize_params(tiny_model[0], "int4")

    def test_fp8_where_available(self, tiny_model):
        from paddle_tpu.framework import jax_compat
        from paddle_tpu.models import gpt as G
        if jax_compat.fp8_dtype() is None:
            with pytest.raises(ValueError, match="fp8"):
                G.quantize_params(tiny_model[0], "fp8")
            return
        qp = G.quantize_params(tiny_model[0], "fp8")
        leaf = qp["blocks"]["fc1_w"]
        assert leaf["qw"].dtype == jax_compat.fp8_dtype()
        w = np.asarray(tiny_model[0]["blocks"]["fc1_w"], np.float32)
        back = (np.asarray(leaf["qw"], np.float32)
                * np.asarray(leaf["scale"]))
        # e4m3 keeps ~2-3 mantissa bits: coarse but bounded
        assert float(np.abs(w - back).max()) < 0.1 * float(
            np.abs(w).max()) + 1e-6

    def test_quantize_kv_roundtrip(self):
        from paddle_tpu.models import gpt as G
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(5, 4, 16).astype(np.float32))
        q, s = G.quantize_kv(x)
        assert q.dtype == jnp.int8 and s.shape == (5, 4)
        back = G.dequantize_kv(q, s, jnp.float32)
        err = np.abs(np.asarray(x) - np.asarray(back))
        # per-position-per-head absmax: error <= scale/2 per element
        assert (err <= np.asarray(s)[..., None] / 2 + 1e-7).all()
        # requantizing dequantized content is a fixed point (the chunk
        # path's safety property: bytes never drift)
        q2, s2 = G.quantize_kv(back)
        assert (np.asarray(q2) == np.asarray(q)).all()

    def test_int8_dynamic_matmul_matches_fp(self):
        import jax.numpy as jnp
        from paddle_tpu import quantization as Q
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(6, 32).astype(np.float32))
        w = rng.randn(32, 16).astype(np.float32)
        ws = np.abs(w).max(0) / 127.0
        wq = jnp.asarray(np.clip(np.round(w / ws), -127, 127)
                         .astype(np.int8))
        got = np.asarray(Q.int8_dynamic_matmul(x, wq, jnp.asarray(ws)))
        want = np.asarray(x) @ w
        assert np.abs(got - want).max() < 0.05 * np.abs(want).max() + 1e-3

    def test_int8_dynamic_scale_is_batch_invariant(self):
        """Regression (review finding): the dynamic activation scale is
        per-ROW — a row's output must not change when it shares a batch
        with a huge-magnitude neighbor, or retries in a different batch
        mix would break the token-exact retry guarantee."""
        import jax.numpy as jnp
        from paddle_tpu import quantization as Q
        rng = np.random.RandomState(2)
        row = rng.randn(1, 32).astype(np.float32)
        loud = 1000.0 * rng.randn(1, 32).astype(np.float32)
        w = rng.randn(32, 16).astype(np.float32)
        ws = jnp.asarray(np.abs(w).max(0) / 127.0)
        wq = jnp.asarray(np.clip(np.round(w / np.asarray(ws)), -127, 127)
                         .astype(np.int8))
        alone = np.asarray(Q.int8_dynamic_matmul(jnp.asarray(row), wq, ws))
        stacked = np.asarray(Q.int8_dynamic_matmul(
            jnp.asarray(np.concatenate([row, loud])), wq, ws))[:1]
        assert (alone == stacked).all()


# --------------------------------------------------------------------------
# quantized engine vs fp32 engine (the accuracy-budget gate)
# --------------------------------------------------------------------------

class TestQuantEngineBudget:
    def test_churn_parity_within_budget(self, tiny_model):
        """int8 weights + int8 KV vs the fp32 paged engine over churned
        mixed-length traffic (wave + chunked admissions): greedy tokens
        EXACT, per-token logits within the declared budget."""
        fp = _make_engine(tiny_model, capture_logits=True,
                          prefill_chunk=8)
        q = _make_engine(tiny_model, capture_logits=True, prefill_chunk=8,
                         quant="int8", kv_dtype="int8")
        fp.warmup()
        assert q.warmup() >= 1
        trace = _trace(10)
        rf = [fp.submit(p, m) for p, m in trace]
        rq = [q.submit(p, m) for p, m in trace]
        fp.run()
        q.run()
        st = q.stats()
        assert st["decode_compiles"] == 1
        assert st["slot_occupancy_peak"] >= 2      # churn really batched
        max_err = 0.0
        for a, b in zip(rf, rq):
            assert a.tokens == b.tokens, (a.id, a.tokens, b.tokens)
            for la, lb in zip(a.logits, b.logits):
                max_err = max(max_err, float(np.abs(la - lb).max()))
        assert 0 < max_err <= LOGIT_BUDGET, max_err
        assert st["pages_in_use"] == 0             # nothing leaked
        assert st["quant_matmuls"] > 0
        assert st["kv_quant_bytes_saved"] > 0

    def test_zero_steady_state_compiles(self, tiny_model):
        from paddle_tpu.observability import metrics
        q = _make_engine(tiny_model, prefill_chunk=8, quant="int8",
                         kv_dtype="int8")
        q.warmup()
        before = metrics.counter("compile.count").value
        for p, m in _trace(8, seed=11):
            q.submit(p, m)
        q.run()
        assert metrics.counter("compile.count").value == before, \
            "quantized steady state retraced"
        assert q.stats()["decode_compiles"] == 1

    def test_weight_only_quant_on_slot_engine(self, tiny_model):
        """quant= is engine-agnostic: the slot-contiguous engine's
        executables take the same quantized pytree."""
        from paddle_tpu.inference.serving import ServingEngine
        params, cfg = tiny_model
        fp = ServingEngine(tiny_model, slots=2, max_len=32,
                           seq_buckets=(8, 16), batch_buckets=(1, 2),
                           capture_logits=True)
        q = ServingEngine(tiny_model, slots=2, max_len=32,
                          seq_buckets=(8, 16), batch_buckets=(1, 2),
                          capture_logits=True, quant="int8")
        fp.warmup()
        q.warmup()
        trace = _trace(4, seed=5)
        rf = [fp.submit(p, m) for p, m in trace]
        rq = [q.submit(p, m) for p, m in trace]
        fp.run()
        q.run()
        for a, b in zip(rf, rq):
            assert a.tokens == b.tokens
            for la, lb in zip(a.logits, b.logits):
                assert float(np.abs(la - lb).max()) <= LOGIT_BUDGET

    def test_kv_accounting_matches_actual_dtypes(self, tiny_model):
        """Satellite: byte accounting derives from the REAL cache
        arrays — int8 pages + fp32 scale rows — never an assumed
        4-byte element."""
        params, cfg = tiny_model
        q = _make_engine(tiny_model, quant="int8", kv_dtype="int8")
        st = q.stats()
        P, ps = q._num_pages, q._page_size
        L, nh, hd = cfg.num_layers, cfg.num_heads, cfg.head_dim
        expect = 2 * L * P * ps * nh * (hd + 4)    # int8 k/v + f32 scales
        assert st["kv_bytes_total"] == expect
        fp = _make_engine(tiny_model)
        assert fp.stats()["kv_bytes_total"] == 2 * L * P * ps * nh * hd * 4
        # the saved-bytes counter is the honest difference
        assert st["kv_quant_bytes_saved"] == \
            fp.stats()["kv_bytes_total"] - st["kv_bytes_total"]
        # reserved bytes track in-use pages at the quantized page cost
        q.warmup()
        r = q.submit(np.arange(1, 10, dtype=np.int32), 4)
        q.step()
        st2 = q.stats()
        page_bytes = expect // P
        assert st2["kv_bytes_reserved"] == \
            st2["pages_in_use"] * page_bytes
        q.run()


# --------------------------------------------------------------------------
# quantized pages: prefix reuse, COW, preemption
# --------------------------------------------------------------------------

class TestQuantPages:
    def test_prefix_reuse_attestation_on_quant_pages(self, tiny_model):
        """The satellite's attestation: a second identical prompt on the
        int8 pool allocates ZERO new pages and decodes identically."""
        q = _make_engine(tiny_model, page_size=4, quant="int8",
                         kv_dtype="int8")
        q.warmup()
        sys_prompt = np.arange(1, 11, dtype=np.int32)   # 10 tokens, 3 pages
        r1 = q.submit(sys_prompt, 4)
        q.run()
        s1 = q.stats()
        r2 = q.submit(sys_prompt, 4)
        q.run()
        s2 = q.stats()
        assert s2["prefix_page_hits"] - s1["prefix_page_hits"] == 3
        assert s2["prefix_page_misses"] - s1["prefix_page_misses"] == 0
        assert r1.tokens == r2.tokens

    def test_cow_on_int8_scale_page_pairs(self, tiny_model):
        """Two in-flight requests sharing a quantized prefix: COW must
        copy the int8 bytes AND the scale rows (a page without its
        scales dequantizes to garbage) — caught by comparing both
        requests against an unshared run of the same prompt."""
        prompt = np.arange(20, 30, dtype=np.int32)
        solo = _make_engine(tiny_model, page_size=4, quant="int8",
                            kv_dtype="int8")
        solo.warmup()
        ref = solo.submit(prompt, 6)
        solo.run()
        q = _make_engine(tiny_model, page_size=4, quant="int8",
                         kv_dtype="int8")
        q.warmup()
        ra = q.submit(prompt, 6)
        rb = q.submit(prompt, 6)
        q.run()
        assert q.stats()["cow_copies"] >= 1
        assert ra.tokens == ref.tokens
        assert rb.tokens == ref.tokens

    def test_injected_exhaustion_preemption_retry_parity(self, tiny_model):
        """An injected page_exhaustion preempts a quantized request; its
        re-prefilled retry must land the SAME tokens a fault-free run
        produces (deterministic quantization => deterministic retry)."""
        from paddle_tpu.testing import faults
        trace = [(np.arange(1, 6, dtype=np.int32), 6),
                 (np.arange(2, 7, dtype=np.int32), 6)]
        clean = _make_engine(tiny_model, slots=2, seq_buckets=(16,),
                             quant="int8", kv_dtype="int8")
        clean.warmup()
        want = [clean.submit(p, m) for p, m in trace]
        clean.run()
        faults.clear()
        faults.install("page_exhaustion:step=2")
        try:
            q = _make_engine(tiny_model, slots=2, seq_buckets=(16,),
                             quant="int8", kv_dtype="int8")
            q.warmup()
            got = [q.submit(p, m) for p, m in trace]
            done = q.run(max_steps=200)
            st = q.stats()
            assert st["preemptions"] == 1
            assert len(done) == 2
            assert sum(r.preemptions for r in got) == 1
            for w, g in zip(want, got):
                assert w.tokens == g.tokens, (g.id, w.tokens, g.tokens)
            assert st["pages_in_use"] == 0
        finally:
            faults.clear()

    def test_engine_error_rebuilds_quant_pool(self, tiny_model):
        """The slot-leak fix on the int8 pool: a mid-step failure frees
        pages, rebuilds pool + scale arrays, and retries token-exact."""
        from paddle_tpu.testing import faults
        faults.clear()
        faults.install("engine_error:step=2")
        try:
            q = _make_engine(tiny_model, slots=2, quant="int8",
                             kv_dtype="int8")
            q.warmup()
            a = q.submit(np.arange(1, 8, dtype=np.int32), 5)
            b = q.submit(np.arange(2, 9, dtype=np.int32), 5)
            with pytest.raises(faults.InjectedFault):
                q.run()
            victims = q.take_aborted()
            assert victims
            assert q.stats()["pages_in_use"] == 0
            for v in victims:
                q.submit(v.reset_for_retry())
            q.run()
            faults.clear()
            clean = _make_engine(tiny_model, slots=2, quant="int8",
                                 kv_dtype="int8")
            clean.warmup()
            ca = clean.submit(a.prompt, a.max_new_tokens)
            cb = clean.submit(b.prompt, b.max_new_tokens)
            clean.run()
            assert a.tokens == ca.tokens
            assert b.tokens == cb.tokens
        finally:
            faults.clear()

    def test_hash_salt_separates_numeric_contracts(self):
        """Satellite: the prefix-page content keys are salted with the
        quant config — identical prompts under different contracts can
        never produce colliding keys (a mixed fleet comparing keys
        across replicas must not alias their pages)."""
        from paddle_tpu.inference.kv_pager import KVPager
        prompt = np.arange(1, 11)
        a = KVPager(17, 4, slots=1, hash_key="quant=none/kv=fp")
        b = KVPager(17, 4, slots=1, hash_key="quant=int8/kv=int8")
        c = KVPager(17, 4, slots=1)                 # legacy: unsalted
        ka, kb, kc = (p._prompt_keys(prompt) for p in (a, b, c))
        assert ka != kb
        assert kc not in (ka, kb)

    def test_engine_pager_carries_contract_salt(self, tiny_model):
        q = _make_engine(tiny_model, quant="int8", kv_dtype="int8")
        fp = _make_engine(tiny_model)
        assert q._pager.hash_key == "quant=int8/kv=int8"
        assert fp._pager.hash_key == "quant=none/kv=fp"
        assert q._pager.hash_key != fp._pager.hash_key


# --------------------------------------------------------------------------
# fleet satellites: numeric contract + capacity routing
# --------------------------------------------------------------------------

class TestFleetQuantContract:
    def _fleet_stub(self, spec):
        from paddle_tpu.inference.fleet import ServingFleet
        fleet = ServingFleet.__new__(ServingFleet)
        fleet.model_spec = spec
        fleet._slots = 4
        fleet.dispatch_queue_depth = 4
        return fleet

    def test_contract_match_and_mismatch(self):
        fleet = self._fleet_stub({"paged": True, "quant": "int8",
                                  "kv_dtype": "int8"})
        ok = {"quant": "int8", "kv_dtype": "int8"}
        assert fleet._contract_mismatch(ok) is None
        bad = fleet._contract_mismatch({"quant": None, "kv_dtype": None})
        # the attestation tuple grew tp + role in ISSUE 15, pp in 20
        assert bad == ((None, None, None, 1, 1, "unified"),
                       ("int8", "int8", None, 1, 1, "unified"))
        # fp32 fleet rejects a quantized replica too
        fp = self._fleet_stub({"paged": True})
        assert fp._contract_mismatch({"quant": None,
                                      "kv_dtype": None}) is None
        assert fp._contract_mismatch(ok) is not None

    def test_worker_spec_builds_quant_engine(self, tiny_model):
        """The replica spec's quant/kv_dtype reach the engine and echo
        back through stats (what the hello attestation reads)."""
        from paddle_tpu.inference.fleet_worker import _build_engine
        eng = _build_engine({"cfg": {
            "vocab_size": 256, "hidden_size": 32, "num_layers": 2,
            "num_heads": 2, "max_seq_len": 64, "dtype": "float32",
            "use_flash": False, "remat": False},
            "paged": True, "slots": 2, "max_len": 32, "page_size": 8,
            "seq_buckets": [8, 16], "batch_buckets": [1],
            "quant": "int8", "kv_dtype": "int8"})
        st = eng.stats()
        assert st["quant"] == "int8" and st["kv_dtype"] == "int8"

    def test_spec_kv_dtype_without_paged_fails_fast(self):
        """Regression (review finding): a spec the engine cannot honor
        must fail in the CALLER's process, not as N permanently-dead
        replicas after hello-attestation churn."""
        from paddle_tpu.inference.fleet import ServingFleet
        from paddle_tpu.inference.fleet_worker import _build_engine
        with pytest.raises(ValueError, match="paged"):
            ServingFleet({"quant": "int8", "kv_dtype": "int8"},
                         replicas=1)
        with pytest.raises(ValueError, match="paged"):
            _build_engine({"kv_dtype": "int8"})
        # a typo'd quant mode must fail at construction too, not as N
        # replicas crashing in gpt.quantize_params before hello
        with pytest.raises(ValueError, match="quant mode"):
            ServingFleet({"paged": True, "quant": "int4"}, replicas=1)
        with pytest.raises(ValueError, match="kv_dtype"):
            ServingFleet({"paged": True, "kv_dtype": "fp8"}, replicas=1)

    def test_engine_kv_dtype_rejects_cache_dtype(self, tiny_model):
        with pytest.raises(ValueError, match="mutually exclusive"):
            _make_engine(tiny_model, kv_dtype="int8",
                         cache_dtype="float32")

    def test_capacity_routing_in_page_units(self):
        """Satellite: routing math is PAGE-denominated, so an int8
        replica whose pool holds ~4x the tokens per byte budget routes
        exactly like its stats say — no 4-byte assumption anywhere."""
        fleet = self._fleet_stub({"paged": True, "quant": "int8",
                                  "kv_dtype": "int8"})

        class _R:
            def __init__(self, stats, inflight=0):
                self.last_stats = stats
                self.inflight = dict.fromkeys(range(inflight))

        # an int8 replica at the same BYTE budget reports ~4x the free
        # pages of its fp twin; capacity scales with it
        q = _R({"slots": 4, "pages_free": 96, "kv_dtype": "int8",
                "pages_per_request_est": 3})
        fp = _R({"slots": 4, "pages_free": 24, "kv_dtype": None,
                 "pages_per_request_est": 3})
        assert fleet._capacity(q) == 8               # slot bound wins
        assert fleet._capacity(fp) == 8
        starved_q = _R({"slots": 4, "pages_free": 9, "kv_dtype": "int8",
                        "pages_per_request_est": 3})
        assert fleet._capacity(starved_q) == 3       # 9 // 3


# --------------------------------------------------------------------------
# fused dequant kernels (interpret mode) — slow tier
# --------------------------------------------------------------------------

@pytest.mark.slow
class TestDequantMatmulKernel:
    @pytest.mark.parametrize("M,K,N", [
        (8, 128, 256),
        (128, 256, 128),
        (32, 128, 512),
    ])
    def test_kernel_matches_lax_fallback(self, M, K, N):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.dequant_matmul import (
            _dqmm_tpu, _pick_blocks, _ref_dequant_matmul)
        rng = np.random.RandomState(M + N)
        x = jnp.asarray(rng.randn(M, K).astype(np.float32))
        wq = jnp.asarray(rng.randint(-127, 128, (K, N)).astype(np.int8))
        s = jnp.asarray((rng.rand(N).astype(np.float32) + 0.1) / 64)
        blocks = _pick_blocks(M, K, N, 4)
        assert blocks is not None
        ref = _ref_dequant_matmul(x, wq, s)
        got = _dqmm_tpu(x, wq, s, *blocks, interpret=True)
        denom = max(1e-6, float(jnp.abs(ref).max()))
        assert float(jnp.abs(ref - got).max()) / denom < 1e-5

    def test_public_entry_reshapes_and_counts(self):
        import jax.numpy as jnp
        from paddle_tpu.observability import metrics
        from paddle_tpu.ops.pallas.dequant_matmul import dequant_matmul
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 4, 128).astype(np.float32))
        wq = jnp.asarray(rng.randint(-127, 128, (128, 128))
                         .astype(np.int8))
        s = jnp.asarray(np.full((128,), 0.01, np.float32))
        before = metrics.counter("serving.dequant_kernel_calls").value
        out = dequant_matmul(x, wq, s, interpret=True)
        assert out.shape == (2, 4, 128)
        assert metrics.counter("serving.dequant_kernel_calls").value \
            == before + 1

    def test_decode_sized_m_pads_into_kernel(self):
        """Regression (review finding): M = slots (a handful of decode
        lanes) sits below the sublane minimum — the kernel must pad
        rows up and slice back, not silently fall back to float weights
        on exactly the memory-bound path it exists for."""
        import jax.numpy as jnp
        from paddle_tpu.observability import metrics
        from paddle_tpu.ops.pallas.dequant_matmul import (
            _ref_dequant_matmul, dequant_matmul)
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(3, 128).astype(np.float32))   # M=3
        wq = jnp.asarray(rng.randint(-127, 128, (128, 256))
                         .astype(np.int8))
        s = jnp.asarray((rng.rand(256).astype(np.float32) + 0.1) / 64)
        before = metrics.counter("serving.dequant_kernel_calls").value
        got = dequant_matmul(x, wq, s, interpret=True)
        assert metrics.counter("serving.dequant_kernel_calls").value \
            == before + 1, "decode-sized M fell back to the lax path"
        ref = _ref_dequant_matmul(x, wq, s)
        denom = max(1e-6, float(jnp.abs(ref).max()))
        assert float(jnp.abs(ref - got).max()) / denom < 1e-5


@pytest.mark.slow
class TestPagedAttentionQuantKernel:
    @pytest.mark.parametrize("S,nh,hd,P,ps,maxP", [
        (4, 4, 16, 12, 8, 4),
        (2, 2, 64, 6, 16, 2),
        (3, 4, 32, 16, 8, 6),
    ])
    def test_kernel_matches_lax_fallback(self, S, nh, hd, P, ps, maxP):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.paged_attn import (
            _paged_attention_quant_tpu, _ref_paged_attention_quant)
        rng = np.random.RandomState(S + P)
        q = jnp.asarray(rng.randn(S, 1, nh, hd).astype(np.float32))
        kq = jnp.asarray(rng.randint(-127, 128, (P, ps, nh, hd))
                         .astype(np.int8))
        vq = jnp.asarray(rng.randint(-127, 128, (P, ps, nh, hd))
                         .astype(np.int8))
        ks = jnp.asarray((rng.rand(P, ps, nh).astype(np.float32)
                          + 0.05) / 64)
        vs = jnp.asarray((rng.rand(P, ps, nh).astype(np.float32)
                          + 0.05) / 64)
        pt = jnp.asarray(rng.randint(0, P, (S, maxP)).astype(np.int32))
        lens = jnp.asarray(
            rng.randint(0, maxP * ps, (S,)).astype(np.int32))
        ref = _ref_paged_attention_quant(q, kq, ks, vq, vs, pt, lens)
        got = _paged_attention_quant_tpu(q, kq, ks, vq, vs, pt, lens,
                                         interpret=True)
        assert float(jnp.abs(ref - got).max()) < 1e-5

    def test_kernel_matches_fallback_bf16(self):
        """The compute-dtype casts around the probs @ V contraction must
        mirror the fallback's (vc.astype(cd)) — float32 tests cannot see
        a missing cast; bf16 can."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.paged_attn import (
            _paged_attention_quant_tpu, _ref_paged_attention_quant)
        rng = np.random.RandomState(9)
        q = jnp.asarray(rng.randn(3, 1, 2, 32)).astype(jnp.bfloat16)
        kq = jnp.asarray(rng.randint(-127, 128, (8, 8, 2, 32))
                         .astype(np.int8))
        vq = jnp.asarray(rng.randint(-127, 128, (8, 8, 2, 32))
                         .astype(np.int8))
        ks = jnp.asarray((rng.rand(8, 8, 2).astype(np.float32)
                          + 0.05) / 64)
        vs = jnp.asarray((rng.rand(8, 8, 2).astype(np.float32)
                          + 0.05) / 64)
        pt = jnp.asarray(rng.randint(0, 8, (3, 3)).astype(np.int32))
        lens = jnp.asarray(rng.randint(0, 24, (3,)).astype(np.int32))
        ref = _ref_paged_attention_quant(q, kq, ks, vq, vs, pt, lens)
        got = _paged_attention_quant_tpu(q, kq, ks, vq, vs, pt, lens,
                                         interpret=True)
        diff = jnp.abs(ref.astype(jnp.float32)
                       - got.astype(jnp.float32))
        # bf16 accumulate: identical dtype semantics, bf16-ulp noise
        assert float(diff.max()) < 2e-2

    def test_kernel_len_zero_lane(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.paged_attn import (
            _paged_attention_quant_tpu, _ref_paged_attention_quant)
        rng = np.random.RandomState(7)
        q = jnp.asarray(rng.randn(2, 1, 2, 16).astype(np.float32))
        kq = jnp.asarray(rng.randint(-127, 128, (5, 8, 2, 16))
                         .astype(np.int8))
        vq = jnp.asarray(rng.randint(-127, 128, (5, 8, 2, 16))
                         .astype(np.int8))
        ks = jnp.asarray(np.full((5, 8, 2), 0.02, np.float32))
        vs = jnp.asarray(np.full((5, 8, 2), 0.02, np.float32))
        pt = jnp.asarray(rng.randint(0, 5, (2, 2)).astype(np.int32))
        lens = jnp.asarray(np.array([0, 9], np.int32))
        ref = _ref_paged_attention_quant(q, kq, ks, vq, vs, pt, lens)
        got = _paged_attention_quant_tpu(q, kq, ks, vq, vs, pt, lens,
                                         interpret=True)
        assert float(jnp.abs(ref - got).max()) < 1e-5
