"""fluid compatibility façade: the reference-era spelling must run
unmodified on the TPU-native core (ref: python/paddle/fluid)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle

fluid = paddle.fluid


class TestFluidDygraph:
    def test_guard_and_layers(self):
        with fluid.dygraph.guard():
            x = fluid.dygraph.to_variable(
                np.random.RandomState(0).randn(4, 3).astype("float32"))
            lin = fluid.dygraph.Linear(3, 5, act="relu")
            y = lin(x)
            assert y.shape == [4, 5] and (y.numpy() >= 0).all()
            conv = fluid.dygraph.Conv2D(1, 2, 3, act="sigmoid")
            img = fluid.dygraph.to_variable(
                np.random.RandomState(1).randn(1, 1, 8, 8).astype("float32"))
            out = conv(img)
            assert out.shape == [1, 2, 6, 6]
            assert (out.numpy() > 0).all() and (out.numpy() < 1).all()
            emb = fluid.dygraph.Embedding([10, 4])
            assert emb(fluid.dygraph.to_variable(
                np.array([1, 2]))).shape == [2, 4]
            pool = fluid.dygraph.Pool2D(2, "max", 2)
            assert pool(img).shape == [1, 1, 4, 4]
            gp = fluid.dygraph.Pool2D(global_pooling=True, pool_type="avg")
            assert gp(img).shape == [1, 1, 1, 1]
            bn = fluid.dygraph.BatchNorm(2, act="relu")
            assert bn(out).shape == [1, 2, 6, 6]
            ln = fluid.dygraph.LayerNorm([8])
            assert ln(fluid.dygraph.to_variable(
                np.ones((2, 8), np.float32))).shape == [2, 8]

    def test_backward_minimize_trains(self):
        with fluid.dygraph.guard():
            rng = np.random.RandomState(0)
            xv = rng.randn(32, 4).astype("float32")
            yv = (xv @ rng.randn(4, 1).astype("float32"))
            lin = fluid.dygraph.Linear(4, 1)
            opt = fluid.optimizer.SGDOptimizer(
                0.1, parameter_list=lin.parameters())
            first = last = None
            for _ in range(30):
                x = fluid.dygraph.to_variable(xv)
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square_error_cost(
                        lin(x), fluid.dygraph.to_variable(yv)))
                loss.backward()
                opt.minimize(loss)
                opt.clear_grad()
                first = first if first is not None else float(loss)
                last = float(loss)
            assert last < first * 0.2

    def test_save_load_dygraph(self):
        with fluid.dygraph.guard():
            lin = fluid.dygraph.Linear(3, 2)
            path = os.path.join(tempfile.mkdtemp(), "m")
            fluid.dygraph.save_dygraph(lin.state_dict(), path)
            params, opt = fluid.dygraph.load_dygraph(path)
            assert opt is None
            lin2 = fluid.dygraph.Linear(3, 2)
            lin2.set_state_dict(params)
            np.testing.assert_allclose(np.asarray(lin2.weight.numpy()),
                                       np.asarray(lin.weight.numpy()))


class TestFluidStatic:
    def setup_method(self, m):
        paddle.enable_static()

    def teardown_method(self, m):
        paddle.disable_static()

    def test_fc_regression_trains(self):
        prog, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, start):
            x = fluid.layers.data("x", [4])
            yt = fluid.layers.data("y", [1])
            h = fluid.layers.relu(fluid.layers.fc(x, 16))
            yp = fluid.layers.fc(h, 1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(yp, yt))
            opt = fluid.optimizer.SGDOptimizer(0.05)
            opt.minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(start)
            rng = np.random.RandomState(0)
            xv = rng.randn(16, 4).astype("float32")
            yv = xv.sum(1, keepdims=True).astype("float32") * 0.3
            first = last = None
            for _ in range(25):
                (lv,) = exe.run(prog, feed={"x": xv, "y": yv},
                                fetch_list=[loss])
                first = first if first is not None else float(lv)
                last = float(lv)
        assert last < first * 0.3

    def test_inference_model_roundtrip(self):
        prog, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, start):
            x = fluid.layers.data("img", [3, 4], append_batch_size=False)
            y = fluid.layers.fc(x, 2)
            exe = fluid.Executor()
            d = tempfile.mkdtemp()
            fluid.io.save_inference_model(d, ["img"], [y], exe,
                                          main_program=prog)
            prog2, feeds, fetches = fluid.io.load_inference_model(d, exe)
            (out,) = exe.run(prog2, feed={"img": np.ones((3, 4), "float32")},
                             fetch_list=fetches)
        assert out.shape == (3, 2)


class TestFluidLayersOps:
    def test_elementwise_axis_broadcast(self):
        a = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
        b = paddle.to_tensor(np.arange(3, dtype=np.float32))
        c = fluid.layers.elementwise_add(a, b, axis=1)
        assert c.shape == [2, 3, 4]
        assert float(c.numpy()[0, 2, 0]) == 3.0

    def test_fill_expand_assign(self):
        d = fluid.layers.fill_constant([2, 2], "float32", 7.0)
        assert (d.numpy() == 7).all()
        e = fluid.layers.expand(
            paddle.to_tensor(np.ones((1, 2), np.float32)), [3, 1])
        assert e.shape == [3, 2]
        f = fluid.layers.fill_constant_batch_size_like(e, [1, 5], "float32",
                                                       2.0)
        assert f.shape == [3, 5]

    def test_cross_entropy_takes_probs(self):
        probs = paddle.to_tensor(np.array([[0.9, 0.1]], np.float32))
        ce = fluid.layers.cross_entropy(probs, paddle.to_tensor(np.array([0])))
        assert ce.shape == [1, 1]
        np.testing.assert_allclose(float(ce.numpy()[0, 0]), -np.log(0.9),
                                   atol=1e-5)

    def test_softmax_with_cross_entropy_per_sample(self):
        logits = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 5).astype("float32"))
        lbl = paddle.to_tensor(np.random.RandomState(1).randint(0, 5, (8, 1)))
        loss, sm = fluid.layers.softmax_with_cross_entropy(
            logits, lbl, return_softmax=True)
        assert loss.shape == [8, 1]
        assert sm.shape == [8, 5]
        np.testing.assert_allclose(sm.numpy().sum(-1), np.ones(8), atol=1e-5)
        # golden: manual log-softmax gather
        lp = logits.numpy() - np.log(
            np.exp(logits.numpy()).sum(-1, keepdims=True))
        ref = -np.take_along_axis(lp, np.asarray(lbl.numpy()), axis=1)
        np.testing.assert_allclose(loss.numpy(), ref, atol=1e-5)

    def test_mul_reduce_scale(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        y = paddle.to_tensor(np.ones((3, 4), np.float32))
        assert fluid.layers.mul(x, y).shape == [2, 4]
        s = fluid.layers.scale(x, scale=2.0, bias=1.0)
        assert float(s.numpy()[0, 0]) == 3.0
        r = fluid.layers.reduce_sum(x, dim=1, keep_dim=True)
        assert r.shape == [2, 1]

    def test_dropout_modes(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        # downgrade_in_infer at test time multiplies by keep-prob... the
        # reference keeps values at inference; train-mode zeros some
        out = fluid.layers.dropout(x, 0.5, is_test=True)
        assert np.isfinite(out.numpy()).all()

    def test_control_flow_reexports(self):
        assert fluid.layers.cond is paddle.static.cond
        assert fluid.layers.while_loop is paddle.static.while_loop

    def test_initializer_aliases(self):
        init = fluid.initializer.ConstantInitializer(3.0)
        w = paddle.create_parameter([2, 2], "float32", attr=paddle.ParamAttr(
            initializer=init))
        assert (np.asarray(w.numpy()) == 3.0).all()
        assert fluid.initializer.MSRAInitializer is not None

    def test_core_and_places(self):
        assert isinstance(fluid.CPUPlace(), paddle.CPUPlace)
        assert fluid.core.get_cuda_device_count() == 0
        assert fluid.core.VarBase is paddle.Tensor

    def test_clip_by_norm(self):
        v = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
        out = fluid.layers.clip_by_norm(v, 1.0)
        np.testing.assert_allclose(np.linalg.norm(out.numpy()), 1.0,
                                   atol=1e-5)

    def test_flags(self):
        fluid.set_flags({"FLAGS_fraction_of_gpu_memory_to_use": 0.5})
        assert fluid.get_flags("FLAGS_fraction_of_gpu_memory_to_use") == {
            "FLAGS_fraction_of_gpu_memory_to_use": 0.5}


class TestFluidNets:
    def test_simple_img_conv_pool(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 16, 16).astype("float32"))
        out = fluid.nets.simple_img_conv_pool(x, 8, 3, 2, 2,
                                              conv_padding=1, act="relu")
        assert out.shape == [2, 8, 8, 8]
        assert (out.numpy() >= 0).all()

    def test_img_conv_group_vgg_block(self):
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 3, 8, 8).astype("float32"))
        out = fluid.nets.img_conv_group(x, [4, 4], 2, conv_act="relu",
                                        conv_with_batchnorm=True,
                                        pool_stride=2)
        assert out.shape == [2, 4, 4, 4]

    def test_sequence_conv_pool(self):
        seq = paddle.to_tensor(
            np.random.RandomState(2).randn(3, 6, 8).astype("float32"))
        lens = paddle.to_tensor(np.array([6, 4, 2]))
        out = fluid.nets.sequence_conv_pool(seq, lens, 10, 3)
        assert out.shape == [3, 10]

    def test_sdpa_and_glu(self):
        q = paddle.to_tensor(
            np.random.RandomState(3).randn(2, 5, 8).astype("float32"))
        assert fluid.nets.scaled_dot_product_attention(
            q, q, q, num_heads=2).shape == [2, 5, 8]
        assert fluid.nets.glu(q).shape == [2, 5, 4]

    def test_module_aliases(self):
        assert fluid.backward.append_backward is paddle.static.append_backward
        with fluid.unique_name.guard():
            pass
        import paddle_tpu.regularizer as R
        assert R.L2DecayRegularizer is R.L2Decay


class TestFluidDygraphLongTail:
    def test_layer_wrappers(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            x = d.to_variable(
                np.random.RandomState(0).randn(1, 2, 5, 5).astype("float32"))
            assert d.Conv2DTranspose(2, 3, 3)(x).shape == [1, 3, 7, 7]
            v = d.to_variable(
                np.random.RandomState(1).randn(1, 2, 3, 4, 4)
                .astype("float32"))
            assert d.Conv3D(2, 3, 3)(v).shape[1] == 3
            assert d.GroupNorm(4, 2)(d.to_variable(
                np.random.RandomState(2).randn(2, 4, 3, 3)
                .astype("float32"))).shape == [2, 4, 3, 3]
            b = d.BilinearTensorProduct(3, 4, 5)
            out = b(d.to_variable(np.ones((2, 3), np.float32)),
                    d.to_variable(np.ones((2, 4), np.float32)))
            assert out.shape == [2, 5]
            p = d.PRelu(mode="all")
            assert p(x).shape == x.shape

    def test_nce_layer_trains(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            nce = d.NCE(50, 8, num_neg_samples=5, seed=3)
            opt = fluid.optimizer.SGDOptimizer(
                0.5, parameter_list=nce.parameters())
            rng = np.random.RandomState(0)
            xv = rng.randn(16, 8).astype("float32")
            lbl = rng.randint(0, 50, (16, 1))
            first = last = None
            for _ in range(20):
                loss = nce(d.to_variable(xv), d.to_variable(lbl)).mean()
                loss.backward()
                opt.minimize(loss)
                opt.clear_grad()
                first = first if first is not None else float(loss)
                last = float(loss)
            assert last < first

    def test_gru_unit_and_tree_conv(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            gru = d.GRUUnit(3 * 6)     # input is pre-projected [B, 3D]
            h, _, _ = gru(d.to_variable(np.ones((2, 18), np.float32)),
                          d.to_variable(np.zeros((2, 6), np.float32)))
            assert h.shape == [2, 6]
            tc = d.TreeConv(8, 4, num_filters=2)
            edges = np.array([[[0, 1], [1, 2], [1, 3], [0, 4]]] * 2,
                             np.int64)
            out = tc(d.to_variable(
                np.random.RandomState(3).randn(2, 5, 8).astype("float32")),
                d.to_variable(edges))
            assert out.shape == [2, 5, 4, 2]

    def test_jit_spellings(self):
        d = fluid.dygraph
        assert d.declarative is paddle.jit.to_static
        assert d.TracedLayer is paddle.jit.TracedLayer
        assert d.CosineDecay is paddle.optimizer.lr.CosineAnnealingDecay


class TestDygraphReviewRegressions:
    def test_gru_unit_preprojected_contract(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            D = 6
            gru = d.GRUUnit(3 * D)
            h, rh, gate = gru(
                d.to_variable(np.random.RandomState(0)
                              .randn(2, 3 * D).astype("float32")),
                d.to_variable(np.zeros((2, D), np.float32)))
            assert h.shape == [2, D]
            assert rh.shape == [2, D] and gate.shape == [2, 3 * D]

    def test_tree_conv_uses_structure(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            tc = d.TreeConv(8, 4, num_filters=2)
            x = d.to_variable(np.random.RandomState(1)
                              .randn(1, 5, 8).astype("float32"))
            e1 = d.to_variable(np.array([[[0, 1], [0, 2], [1, 3]]],
                                        np.int64))
            e2 = d.to_variable(np.array([[[0, 3], [2, 4], [1, 2]]],
                                        np.int64))
            assert np.abs(tc(x, e1).numpy()
                          - tc(x, e2).numpy()).max() > 1e-6

    def test_nce_resamples_negatives(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            nce = d.NCE(100, 8, num_neg_samples=5, seed=7)
            xi = d.to_variable(np.random.RandomState(2)
                               .randn(4, 8).astype("float32"))
            li = d.to_variable(np.random.RandomState(3)
                               .randint(0, 100, (4, 1)))
            assert float(nce(xi, li).sum()) != float(nce(xi, li).sum())

    def test_conv_transpose_output_size(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            # valid output_size range for stride 2, k 4, in 4: [10, 11]
            ct = d.Conv2DTranspose(2, 3, 4, stride=2, output_size=[11, 11])
            out = ct(d.to_variable(
                np.random.randn(1, 2, 4, 4).astype("float32")))
            assert out.shape == [1, 3, 11, 11]
            with pytest.raises(ValueError, match="output_size"):
                bad = d.Conv2DTranspose(2, 3, 4, stride=2,
                                        output_size=[9, 9])
                bad(d.to_variable(
                    np.random.randn(1, 2, 4, 4).astype("float32")))

    def test_instance_norm_all_ranks(self):
        with fluid.dygraph.guard():
            d = fluid.dygraph
            inorm = d.InstanceNorm(4)
            for shape in ((2, 4, 7), (2, 4, 6, 6), (1, 4, 2, 3, 3)):
                x = d.to_variable(np.random.randn(*shape).astype("float32"))
                assert inorm(x).shape == list(shape)

    def test_lars_fluid_wrapper_constructs_and_steps(self):
        with fluid.dygraph.guard():
            lin = fluid.dygraph.Linear(4, 1)
            opt = fluid.optimizer.LarsMomentumOptimizer(
                0.1, parameter_list=lin.parameters())
            loss = lin(fluid.dygraph.to_variable(
                np.ones((2, 4), np.float32))).mean()
            loss.backward()
            opt.minimize(loss)


class TestFluidTopLevelLongTail:
    """fluid.__init__ aggregates the component modules' __all__ (ref
    fluid/framework.py, data_feeder.py, evaluator.py, average.py,
    unique_name.py, profiler.py)."""

    def test_names_resolve(self):
        for n in ("ChunkEvaluator DataFeeder DetectionMAP EditDistance "
                  "L1Decay L1DecayRegularizer L2Decay L2DecayRegularizer "
                  "WeightedAverage cuda_pinned_places device_guard "
                  "generate guard is_compiled_with_xpu require_version "
                  "switch xpu_places profiler DatasetFactory").split():
            assert hasattr(fluid, n), n
        for n in ("cuda_profiler reset_profiler profiler start_profiler "
                  "stop_profiler").split():
            assert hasattr(fluid.profiler, n), n

    def test_weighted_average(self):
        wa = fluid.WeightedAverage()
        wa.add(2.0, 1)
        wa.add(4.0, 3)
        assert abs(wa.eval() - 3.5) < 1e-9
        wa.reset()
        with pytest.raises(ValueError):
            wa.eval()

    def test_require_version(self):
        fluid.require_version("1.8.0")
        with pytest.raises(Exception):
            fluid.require_version("9.0.0")

    def test_data_feeder(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                img = fluid.layers.data("dfc_img", [4])
                lbl = fluid.layers.data("dfc_lbl", [1], dtype="int64")
                s = fluid.layers.reduce_sum(img)
                feeder = fluid.DataFeeder(feed_list=[img, lbl],
                                          place=fluid.CPUPlace())
                fd = feeder.feed([
                    (np.ones(4, "float32"), np.array([1])),
                    (np.full(4, 2.0, "float32"), np.array([0]))])
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                v, = exe.run(main, feed=fd, fetch_list=[s])
                assert float(v) == 12.0
        finally:
            paddle.disable_static()

    def test_profiler_contexts(self):
        with fluid.profiler.profiler():
            (paddle.to_tensor([1.0]) * 2).numpy()
        fluid.profiler.reset_profiler()
        import os
        import tempfile
        p = os.path.join(tempfile.mkdtemp(), "trace.json")
        with fluid.profiler.cuda_profiler(p):
            (paddle.to_tensor([1.0]) * 2).numpy()
        assert os.path.exists(p)
