"""Reference-parity regressions (round-5 review batch): viterbi lengths
and BOS/EOS, RNN sequence_length, conv padding_mode, pooling masks,
Auc anchor, RandomCrop pad_if_needed, full() dtype, round semantics,
MultiHeadAttention dropout placement."""
import itertools

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_viterbi_lengths_and_bos_eos_brute_force():
    from paddle_tpu.text.viterbi import viterbi_decode
    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 5
    em = rng.randn(B, T, N).astype(np.float32)
    tr = rng.randn(N, N).astype(np.float32)
    lens = np.asarray([5, 3, 1], np.int32)

    def brute(b, bos_eos):
        L = lens[b]
        best, path = -1e30, None
        for tags in itertools.product(range(N), repeat=int(L)):
            s = em[b, 0, tags[0]]
            if bos_eos:
                s += tr[N - 2, tags[0]]
            for t in range(1, L):
                s += tr[tags[t - 1], tags[t]] + em[b, t, tags[t]]
            if bos_eos:
                s += tr[tags[L - 1], N - 1]
            if s > best:
                best, path = s, tags
        return best, list(path) + [0] * (T - L)

    for bos_eos in (False, True):
        sc, pa = viterbi_decode(paddle.to_tensor(em), paddle.to_tensor(tr),
                                paddle.to_tensor(lens), bos_eos)
        sc, pa = np.asarray(sc.numpy()), np.asarray(pa.numpy())
        for b in range(B):
            ws, wp = brute(b, bos_eos)
            assert abs(sc[b] - ws) < 1e-4
            assert list(pa[b]) == wp


def test_lstm_sequence_length_final_state_vs_torch_packed():
    rng = np.random.RandomState(0)
    B, T, I, H = 3, 6, 4, 5
    x = rng.randn(B, T, I).astype(np.float32)
    lens = np.asarray([6, 3, 1], np.int64)

    tl = torch.nn.LSTM(I, H, batch_first=True)
    pl = paddle.nn.LSTM(I, H)
    sd = pl.state_dict()
    names = set(sd)
    with torch.no_grad():
        for tn, suffix in (("weight_ih_l0", "weight_ih"),
                           ("weight_hh_l0", "weight_hh"),
                           ("bias_ih_l0", "bias_ih"),
                           ("bias_hh_l0", "bias_hh")):
            cand = [k for k in names if k.endswith(suffix)]
            assert len(cand) == 1
            sd[cand[0]] = paddle.to_tensor(getattr(tl, tn).detach().numpy())
    pl.set_state_dict(sd)

    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.tensor(x), torch.tensor(lens), batch_first=True,
        enforce_sorted=False)
    _, (hn, cn) = tl(packed)
    _, (hp, cp) = pl(paddle.to_tensor(x),
                     sequence_length=paddle.to_tensor(
                         lens.astype(np.int32)))
    np.testing.assert_allclose(hp.numpy()[0], hn.detach().numpy()[0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cp.numpy()[0], cn.detach().numpy()[0],
                               rtol=1e-4, atol=1e-5)


def test_conv2d_padding_mode_reflect_vs_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    tc = torch.nn.Conv2d(3, 4, 3, padding=1, padding_mode="reflect")
    pc = nn.Conv2D(3, 4, 3, padding=1, padding_mode="reflect")
    pc.weight.set_value(paddle.to_tensor(tc.weight.detach().numpy()))
    pc.bias.set_value(paddle.to_tensor(tc.bias.detach().numpy()))
    np.testing.assert_allclose(pc(paddle.to_tensor(x)).numpy(),
                               tc(torch.tensor(x)).detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_max_pool_mask_ceil_mode_vs_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    tout, tmask = torch.nn.functional.max_pool2d(
        torch.tensor(x), 2, 2, 0, ceil_mode=True, return_indices=True)
    pout, pmask = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                               ceil_mode=True, return_mask=True)
    np.testing.assert_allclose(pout.numpy(), tout.numpy(), atol=1e-6)
    np.testing.assert_array_equal(pmask.numpy(), tmask.numpy())


def test_adaptive_max_pool_mask_vs_torch():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 7, 9).astype(np.float32)
    tout, tmask = torch.nn.functional.adaptive_max_pool2d(
        torch.tensor(x), (2, 3), return_indices=True)
    pout, pmask = F.adaptive_max_pool2d(paddle.to_tensor(x), (2, 3),
                                        return_mask=True)
    np.testing.assert_allclose(pout.numpy(), tout.numpy(), atol=1e-6)
    np.testing.assert_array_equal(pmask.numpy(), tmask.numpy())


def test_avg_pool_divisor_override_vs_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    t = torch.nn.functional.avg_pool2d(torch.tensor(x), 2, 2,
                                       divisor_override=3)
    p = F.avg_pool2d(paddle.to_tensor(x), 2, stride=2, divisor_override=3)
    np.testing.assert_allclose(p.numpy(), t.numpy(), atol=1e-6)


def test_auc_includes_origin_anchor():
    m = paddle.metric.Auc()
    # every prediction lands in the top bucket with mixed labels:
    # random ranking -> AUC must be 0.5, not 0.0
    preds = np.asarray([[0.0, 1.0]] * 10, np.float32)
    labels = np.asarray([[1], [0]] * 5, np.int64)
    m.update(paddle.to_tensor(preds), paddle.to_tensor(labels))
    assert abs(m.accumulate() - 0.5) < 1e-6


def test_random_crop_pad_if_needed_pads_width():
    from paddle_tpu.vision.transforms import RandomCrop
    img = np.zeros((32, 20, 3), np.uint8)
    out = RandomCrop(32, pad_if_needed=True)(img)
    assert np.asarray(out).shape[:2] == (32, 32)


def test_full_defaults_to_float32():
    t = paddle.full([2], 7)
    assert str(t.dtype).endswith("float32"), t.dtype
    np.testing.assert_allclose((t / 3).numpy(), [7 / 3] * 2, rtol=1e-6)


def test_round_half_away_from_zero():
    r = paddle.round(paddle.to_tensor(
        np.asarray([0.5, 1.5, 2.5, -0.5, -1.5], np.float32))).numpy()
    np.testing.assert_array_equal(r, [1.0, 2.0, 3.0, -1.0, -2.0])


def test_mha_dropout_on_attention_weights():
    """Eval: no dropout anywhere.  Train with dropout=0.9: outputs must
    DIFFER from eval (dropout active) and the zero-pattern must come
    from attention weights, not the projected output (a post-proj
    dropout would zero entire output entries)."""
    rng = np.random.RandomState(0)
    mha = nn.MultiHeadAttention(8, 2, dropout=0.9)
    x = paddle.to_tensor(rng.randn(2, 4, 8).astype(np.float32))
    mha.eval()
    base = mha(x).numpy()
    out_eval2 = mha(x).numpy()
    np.testing.assert_allclose(base, out_eval2)   # eval deterministic
    mha.train()
    # post-proj dropout(0.9) would zero ~90% of output entries on EVERY
    # seed; attention-weight dropout zeros far fewer (a row only zeroes
    # when every kept weight for it drops).  A single seed sits near the
    # old 0.5 threshold (exactly 0.5 on some platforms), so average the
    # zero-fraction over several seeds and split the two regimes at 0.75.
    fracs = []
    for s in range(6):
        paddle.seed(s)
        out_tr = mha(x).numpy()
        assert not np.allclose(out_tr, base)
        fracs.append((np.abs(out_tr) < 1e-12).mean())
    assert np.mean(fracs) < 0.75, fracs


def test_instance_norm_nhwc_matches_nchw():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 6, 8).astype(np.float32)
    a = F.instance_norm(paddle.to_tensor(x), data_format="NCHW").numpy()
    b = F.instance_norm(paddle.to_tensor(np.transpose(x, (0, 2, 3, 1))),
                        data_format="NHWC").numpy()
    np.testing.assert_allclose(np.transpose(b, (0, 3, 1, 2)), a,
                               rtol=1e-4, atol=1e-5)


class TestFluidContracts:
    def test_save_dygraph_routes_optimizer_state_to_pdopt(self, tmp_path):
        from paddle_tpu.fluid.dygraph import save_dygraph, load_dygraph
        lin = nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(0.001, parameters=lin.parameters())
        loss = (lin(paddle.to_tensor(np.ones((1, 2), np.float32)))).sum()
        loss.backward()
        opt.step()
        base = str(tmp_path / "ckpt")
        save_dygraph(lin.state_dict(), base)
        save_dygraph(opt.state_dict(), base)   # float lr: still .pdopt
        params, optd = load_dygraph(base)
        assert optd is not None
        assert any(k.endswith("weight") or "w_" in k for k in params), \
            list(params)[:4]
        w0 = lin.weight.numpy().copy()
        lin2 = nn.Linear(2, 2)
        lin2.set_state_dict(params)
        np.testing.assert_allclose(lin2.weight.numpy(), w0)

    def test_fluid_fc_era_keywords(self):
        paddle.enable_static()
        try:
            from paddle_tpu import static
            from paddle_tpu.fluid import layers
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("fcx", [None, 4], "float32")
                out = layers.fc(input=x, size=3, act="softmax")
                exe = static.Executor()
                exe.run(startup)
                r, = exe.run(main, feed={
                    "fcx": np.ones((2, 4), np.float32)},
                    fetch_list=[out])
            assert np.asarray(r).shape == (2, 3)
            np.testing.assert_allclose(np.asarray(r).sum(-1), [1.0, 1.0],
                                       rtol=1e-5)
        finally:
            paddle.disable_static()

    def test_mean_iou_wrong_correct_counts(self):
        from paddle_tpu.fluid import layers
        pred = paddle.to_tensor(np.asarray([0, 0, 1, 1], np.int64))
        lbl = paddle.to_tensor(np.asarray([0, 1, 1, 1], np.int64))
        _, wrong, correct = layers.mean_iou(pred, lbl, 2)
        # class0: inter 1, union 2 -> wrong 1, correct 1
        # class1: inter 2, union 3 -> wrong 1, correct 2
        np.testing.assert_array_equal(wrong.numpy(), [1, 1])
        np.testing.assert_array_equal(correct.numpy(), [1, 2])

    def test_fluid_auc_streams_across_calls(self):
        from paddle_tpu.fluid import layers
        rng = np.random.RandomState(0)
        vals = []
        for i in range(3):
            preds = rng.rand(16, 2).astype(np.float32)
            labels = (rng.rand(16, 1) > 0.5).astype(np.int64)
            a, pos, neg = layers.auc(paddle.to_tensor(preds),
                                     paddle.to_tensor(labels),
                                     name="stream_test")
            vals.append(float(a.numpy()))
            assert pos is not None and neg is not None
        # 48 accumulated samples: stat buckets must keep growing
        assert int(np.asarray(pos.numpy()).sum()
                   + np.asarray(neg.numpy()).sum()) == 48

    def test_checkpoint_rewind_keeps_live_run(self, tmp_path):
        import time
        from paddle_tpu.utils.checkpoint import CheckpointManager
        lin = nn.Linear(2, 2)
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for step in (180, 190, 200):
            mgr.save(step, model=lin)
            time.sleep(0.01)
        # rewind: operator retrains from an earlier step
        mgr.save(110, model=lin)
        time.sleep(0.01)
        mgr.save(120, model=lin)
        # the live run's checkpoints survive; auto-resume picks 120
        assert mgr.latest_step() == 120


class TestDetectionIoContracts:
    def test_nms_pads_to_keep_top_k(self):
        from paddle_tpu.vision.detection import multiclass_nms
        rng = np.random.RandomState(0)
        bb = paddle.to_tensor(rng.rand(1, 40, 4).astype(np.float32))
        sc = paddle.to_tensor(rng.rand(1, 2, 40).astype(np.float32))
        out = multiclass_nms(bb, sc, score_threshold=0.0, nms_top_k=50,
                             keep_top_k=100, nms_threshold=0.5)
        assert tuple(out.shape) == (1, 100, 6)
        assert (np.asarray(out.numpy())[0, -1, 0] == -1.0)  # padded row

    def test_generate_proposal_labels_empty_gt_samples_background(self):
        from paddle_tpu.vision.detection import generate_proposal_labels
        rng = np.random.RandomState(0)
        rois = paddle.to_tensor(
            (rng.rand(1, 16, 4) * 50).astype(np.float32))
        gt = paddle.to_tensor(np.zeros((1, 3, 4), np.float32))  # padding
        gt_cls = paddle.to_tensor(np.zeros((1, 3, 1), np.int32))
        crowd = paddle.to_tensor(np.zeros((1, 3, 1), np.int32))
        im_info = paddle.to_tensor(
            np.asarray([[64.0, 64.0, 1.0]], np.float32))
        outs = generate_proposal_labels(
            rois, gt_cls, crowd, gt, im_info,
            batch_size_per_im=8, fg_fraction=0.25, fg_thresh=0.5,
            bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=3)
        labels = np.asarray(outs[1].numpy()).reshape(-1)
        assert (labels == 0).sum() > 0, labels  # backgrounds sampled

    def test_concat_dataset_negative_index(self):
        from paddle_tpu.io import ConcatDataset, Dataset

        class R(Dataset):
            def __init__(self, lo, n):
                self.lo, self.n = lo, n

            def __len__(self):
                return self.n

            def __getitem__(self, i):
                if i < 0:
                    i += self.n
                return self.lo + i

        ds = ConcatDataset([R(0, 3), R(100, 2)])
        assert ds[-1] == 101 and ds[-5] == 0 and ds[4] == 101
        with pytest.raises(IndexError):
            ds[-6]

    def test_random_split_generator_reproducible(self):
        from paddle_tpu.io import random_split, TensorDataset
        ds = TensorDataset([paddle.to_tensor(
            np.arange(20, dtype=np.float32).reshape(20, 1))])
        a1, _ = random_split(ds, [15, 5], generator=123)
        a2, _ = random_split(ds, [15, 5], generator=123)
        assert a1.indices == a2.indices

    def test_loader_backpressure_bounds_pending(self):
        import threading
        import time
        from paddle_tpu.io import DataLoader, Dataset as Ds
        peak = [0]
        inflight = [0]
        lock = threading.Lock()

        class Slow0(Ds):
            def __len__(self):
                return 64

            def __getitem__(self, i):
                with lock:
                    inflight[0] += 1
                    peak[0] = max(peak[0], inflight[0])
                if i == 0:
                    time.sleep(1.0)     # straggler batch 0
                with lock:
                    inflight[0] -= 1
                return np.full(2, float(i), np.float32)

        loader = DataLoader(Slow0(), batch_size=1, num_workers=2,
                            prefetch_factor=2, use_native_ring=False)
        out = [b for b in loader]
        assert len(out) == 64
        np.testing.assert_allclose(np.asarray(out[0].numpy())[0], [0, 0])
