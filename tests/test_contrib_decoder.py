"""fluid.contrib long tail: decoder API, memory_usage, extend_optimizer
(ref fluid/contrib/decoder/beam_search_decoder.py, memory_usage_calc.py,
extend_optimizer/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid


class TestTrainingDecoder:
    def test_teacher_forced_gru_decodes(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                B, T, D, H = 2, 5, 4, 8
                src = fluid.layers.data("td_src", [T, D], dtype="float32")
                h0 = fluid.layers.fc(
                    fluid.layers.reduce_mean(src, dim=1), H)

                cell = fluid.contrib.StateCell(
                    inputs={"x": None},
                    states={"h": fluid.contrib.InitState(init=h0)},
                    out_state="h")

                @cell.state_updater
                def updater(state_cell):
                    x = state_cell.get_input("x")
                    h_prev = state_cell.get_state("h")
                    h = fluid.layers.tanh(
                        fluid.layers.fc(
                            fluid.layers.concat([x, h_prev], axis=1), H))
                    state_cell.set_state("h", h)

                decoder = fluid.contrib.TrainingDecoder(cell)
                with decoder.block():
                    w = decoder.step_input(src)
                    cell.compute_state(inputs={"x": w})
                    cell.update_states()
                    decoder.output(cell.out_state())
                out = decoder()                      # [B, T, H]
                loss = fluid.layers.reduce_mean(out * out)

                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                x = np.random.RandomState(0).randn(B, T, D).astype(
                    "float32")
                o, lv = exe.run(main, feed={"td_src": x},
                                fetch_list=[out, loss])
                assert o.shape == (B, T, H)
                assert np.isfinite(lv).all()
                # recurrence is real: step outputs differ over time
                assert np.abs(o[:, 0] - o[:, 1]).max() > 1e-6
        finally:
            paddle.disable_static()


class TestContribBeamSearchDecoder:
    def test_default_decode_produces_beams(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                B, K, H, V, D = 2, 3, 8, 11, 6
                max_len, end_id = 4, 1
                # beam decode needs concrete row counts at build (static
                # shapes): declare the feed with a FIXED batch dim
                enc = fluid.layers.data("bsd_enc", [B, H],
                                        dtype="float32",
                                        append_batch_size=False)
                # [B*K] rows: replicate encoder state per beam
                enc_bk = fluid.layers.reshape(
                    fluid.layers.expand(
                        fluid.layers.unsqueeze(enc, [1]), [1, K, 1]),
                    [-1, H])

                cell = fluid.contrib.StateCell(
                    inputs={"x": None},
                    states={"h": fluid.contrib.InitState(init=enc_bk)},
                    out_state="h")

                @cell.state_updater
                def updater(sc):
                    x = sc.get_input("x")
                    h = sc.get_state("h")
                    sc.set_state("h", fluid.layers.tanh(fluid.layers.fc(
                        fluid.layers.concat([x, h], axis=1), H)))

                init_ids = paddle.to_tensor(
                    np.zeros((B * K, 1), "int32"))
                sc0 = np.full((B, K), -1e9, "float32")
                sc0[:, 0] = 0.0                      # 1 live beam at t=0
                init_scores = paddle.to_tensor(sc0.reshape(B * K, 1))

                decoder = fluid.contrib.BeamSearchDecoder(
                    state_cell=cell, init_ids=init_ids,
                    init_scores=init_scores, target_dict_dim=V,
                    word_dim=D, topk_size=K, max_len=max_len,
                    beam_size=K, end_id=end_id)
                decoder.decode()
                tr_ids, tr_scores = decoder()

                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                x = np.random.RandomState(1).randn(B, H).astype("float32")
                ids, scores = exe.run(main, feed={"bsd_enc": x},
                                      fetch_list=[tr_ids, tr_scores])
                assert ids.shape == (B, K, max_len)
                assert scores.shape == (B, K, max_len)
                assert ids.min() >= 0 and ids.max() < V
                # beams are distinct hypotheses (not all identical)
                assert not np.all(ids[:, 0] == ids[:, 1])
                # scores accumulate log-probs: non-increasing over time
                # for unfinished rows
                assert np.isfinite(scores).all()
        finally:
            paddle.disable_static()


class TestInitStateShapeForm:
    def test_reference_shape_spelling(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                boot = fluid.layers.data("is_boot", [4, 6],
                                         dtype="float32",
                                         append_batch_size=False)
                st = fluid.contrib.InitState(shape=[-1, 8], value=0.0,
                                             init_boot=boot)
                # shape[0] replaced by boot's batch: [4, 8]
                assert list(st.value.shape) == [4, 8]
                assert float(st.value.numpy().sum()) == 0.0
        finally:
            paddle.disable_static()


class TestMemoryUsage:
    def test_scales_with_batch(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("mu_x", [64], dtype="float32")
                h = fluid.layers.fc(x, 128, activation="relu")
                fluid.layers.fc(h, 10)
                lo1, hi1, u1 = fluid.contrib.memory_usage(main, 1)
                lo64, hi64, u64 = fluid.contrib.memory_usage(main, 64)
                assert lo1 < hi1 and lo64 < hi64

                def in_bytes(v, unit):
                    return v * {"B": 1, "KB": 2**10, "MB": 2**20}[unit]
                # activations scale ~linearly with batch; params are
                # constant — 64x batch must grow the estimate well past
                # the param floor (~38KB here) but far less than 64x
                b1, b64 = in_bytes(lo1, u1), in_bytes(lo64, u64)
                assert b64 > 2 * b1
                assert b64 < 64 * b1
        finally:
            paddle.disable_static()


class TestDecoupledWeightDecay:
    def test_decay_applied_before_update(self):
        SGDW = fluid.contrib.extend_with_decoupled_weight_decay(
            paddle.optimizer.SGD)
        w = paddle.to_tensor(np.array([10.0], "float32"),
                             stop_gradient=False)
        opt = SGDW(learning_rate=0.0, parameters=[w], weight_decay=0.1)
        loss = (w * 0.0).sum()        # zero grad, zero lr: only decay
        loss.backward()
        opt.step()
        np.testing.assert_allclose(float(w.numpy()), 9.0, rtol=1e-6)

    def test_minimize_decays_exactly_once(self):
        SGDW = fluid.contrib.extend_with_decoupled_weight_decay(
            paddle.optimizer.SGD)
        w = paddle.to_tensor(np.array([10.0], "float32"),
                             stop_gradient=False)
        opt = SGDW(weight_decay=0.1, learning_rate=0.0, parameters=[w])
        loss = (w * 0.0).sum()
        opt.minimize(loss)            # must decay once, not coeff^2
        np.testing.assert_allclose(np.asarray(w.numpy()), [9.0],
                                   rtol=1e-6)

    def test_weight_decay_positional_first(self):
        # reference generated-class signature: weight_decay positional
        SGDW = fluid.contrib.extend_with_decoupled_weight_decay(
            paddle.optimizer.SGD)
        w = paddle.to_tensor(np.array([10.0], "float32"),
                             stop_gradient=False)
        opt = SGDW(0.1, learning_rate=0.0, parameters=[w])
        loss = (w * 0.0).sum()
        loss.backward()
        opt.step()
        np.testing.assert_allclose(np.asarray(w.numpy()), [9.0],
                                   rtol=1e-6)

    def test_static_executor_applies_decay(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("wd_x", [2], dtype="float32")
                y = fluid.layers.fc(x, 1, bias_attr=False)
                loss = fluid.layers.reduce_mean(y) * 0.0  # zero grads
                SGDW = fluid.contrib.extend_with_decoupled_weight_decay(
                    paddle.optimizer.SGD)
                opt = SGDW(0.5, learning_rate=0.0)
                opt.minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                p = main.all_parameters()[0]
                before = np.asarray(p.numpy()).copy()
                exe.run(main, feed={"wd_x": np.ones((3, 2), "float32")},
                        fetch_list=[loss])
                after = np.asarray(p.numpy())
                np.testing.assert_allclose(after, before * 0.5, rtol=1e-5)
        finally:
            paddle.disable_static()

    def test_filter_and_training(self):
        AdamX = fluid.contrib.extend_with_decoupled_weight_decay(
            paddle.optimizer.Adam)
        w = paddle.to_tensor(np.array([4.0], "float32"),
                             stop_gradient=False)
        opt = AdamX(learning_rate=0.1, parameters=[w], weight_decay=0.01)
        for _ in range(30):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert abs(float(w.numpy())) < 1.0
