"""Module-level workers for paddle.distributed.spawn tests (multiprocessing
'spawn' pickles the target by qualified name, so they must live in an
importable module, not a test function body)."""
import os


def write_rank(out_dir):
    rank = os.environ.get("PADDLE_TRAINER_ID", "?")
    with open(os.path.join(out_dir, f"rank_{rank}.txt"), "w") as f:
        f.write(rank)


def telemetry_train(telemetry_dir, steps=4):
    """Tiny fixed-seed training loop under a StepTimer, writing this
    rank's JSONL step records + published snapshot into ``telemetry_dir``
    (the 2-process aggregation e2e merges them cross-rank)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.observability import StepTimer, aggregate, timeline

    timeline.configure(telemetry_dir)
    paddle.seed(7)
    net = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.Momentum(0.05, parameters=net.parameters())
    rng = np.random.RandomState(0)
    with StepTimer(name="spawn_e2e", tokens_per_step=32,
                   publish_interval=0) as timer:
        for _ in range(steps):
            x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
            with timer.step():
                loss = (net(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
    aggregate.publish(step=steps)
