"""Module-level worker for paddle.distributed.spawn tests (multiprocessing
'spawn' pickles the target by qualified name, so it must live in an
importable module, not a test function body)."""
import os


def write_rank(out_dir):
    rank = os.environ.get("PADDLE_TRAINER_ID", "?")
    with open(os.path.join(out_dir, f"rank_{rank}.txt"), "w") as f:
        f.write(rank)
