"""Tests for the fluid cell/decode-helper surface + long-tail ops
(ref fluid/layers/rnn.py:62 RNNCell family, :437 rnn, :661 birnn, :3392
lstm_unit, :1742+ decode helpers; nn.py:12755 similarity_focus, :13807
prroi_pool, :14001 continuous_value_model, :14592 deformable_roi_pooling).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_fluid_lstm_cell_and_rnn_golden():
    rng = np.random.RandomState(0)
    B, T, M, D = 2, 4, 3, 5
    x = rng.randn(B, T, M).astype(np.float32) * 0.5
    cell = fluid.layers.LSTMCell(hidden_size=D)
    out, (h, c) = fluid.layers.rnn(cell, paddle.to_tensor(x))
    assert out.shape == [B, T, D]

    # golden: BasicLSTMUnit recurrence {i, j, f, o}, forget_bias 1.0
    w = cell.weight.numpy()
    b = cell.bias.numpy()
    hh = np.zeros((B, D), np.float32)
    cc = np.zeros((B, D), np.float32)
    for t in range(T):
        g = np.concatenate([x[:, t], hh], 1) @ w + b
        i, j, f, o = np.split(g, 4, axis=-1)
        cc = cc * sigmoid(f + 1.0) + sigmoid(i) * np.tanh(j)
        hh = np.tanh(cc) * sigmoid(o)
    np.testing.assert_allclose(out.numpy()[:, -1], hh, atol=1e-5)
    np.testing.assert_allclose(h.numpy(), hh, atol=1e-5)
    np.testing.assert_allclose(c.numpy(), cc, atol=1e-5)


def test_fluid_gru_cell_golden():
    rng = np.random.RandomState(1)
    B, M, D = 3, 4, 5
    x = rng.randn(B, M).astype(np.float32) * 0.5
    h0 = rng.randn(B, D).astype(np.float32) * 0.5
    cell = fluid.layers.GRUCell(hidden_size=D)
    out, h = cell(paddle.to_tensor(x), paddle.to_tensor(h0))

    gw = cell.gate_weight.numpy()
    gb = cell.gate_bias.numpy()
    cw = cell.candidate_weight.numpy()
    cb = cell.candidate_bias.numpy()
    g = sigmoid(np.concatenate([x, h0], 1) @ gw + gb)
    r, u = g[:, :D], g[:, D:]
    cand = np.tanh(np.concatenate([x, r * h0], 1) @ cw + cb)
    want = u * h0 + (1 - u) * cand
    np.testing.assert_allclose(out.numpy(), want, atol=1e-5)
    np.testing.assert_allclose(h.numpy(), want, atol=1e-5)


def test_rnn_sequence_length_and_birnn():
    rng = np.random.RandomState(2)
    B, T, M, D = 2, 5, 3, 4
    x = rng.randn(B, T, M).astype(np.float32)
    lens = np.array([5, 2], np.int32)
    cell = fluid.layers.GRUCell(hidden_size=D)
    out, h = fluid.layers.rnn(cell, paddle.to_tensor(x),
                              sequence_length=paddle.to_tensor(lens))
    o = out.numpy()
    assert np.all(o[1, 2:] == 0)          # padded steps emit zeros
    # final state of row1 equals output at its last valid step
    np.testing.assert_allclose(h.numpy()[1], o[1, 1], atol=1e-6)

    cell_fw = fluid.layers.GRUCell(hidden_size=D)
    cell_bw = fluid.layers.GRUCell(hidden_size=D)
    bout, (hf, hb) = fluid.layers.birnn(cell_fw, cell_bw,
                                        paddle.to_tensor(x))
    assert bout.shape == [B, T, 2 * D]


def test_lstm_unit_golden():
    rng = np.random.RandomState(3)
    B, M, D = 2, 3, 4
    x = rng.randn(B, M).astype(np.float32)
    h0 = rng.randn(B, D).astype(np.float32)
    c0 = rng.randn(B, D).astype(np.float32)
    h, c = fluid.layers.lstm_unit(paddle.to_tensor(x),
                                  paddle.to_tensor(h0),
                                  paddle.to_tensor(c0), forget_bias=0.5)
    assert h.shape == [B, D] and c.shape == [B, D]
    assert np.isfinite(h.numpy()).all()


def test_basic_decoder_greedy_helper():
    """GreedyEmbeddingHelper + BasicDecoder through dynamic_decode: a
    rigged output layer that always emits the end token finishes in one
    step with per-sequence lengths 1."""
    rng = np.random.RandomState(4)
    V, D = 7, 5
    emb = rng.randn(V, D).astype(np.float32)

    def embedding_fn(ids):
        idv = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids)
        return paddle.to_tensor(emb[idv.reshape(-1)])

    cell = fluid.layers.GRUCell(hidden_size=D)

    def output_fn(h):
        # force logits peaking at id 3 (the end token)
        logits = np.zeros((int(h.shape[0]), V), np.float32)
        logits[:, 3] = 10.0
        return paddle.to_tensor(logits)

    helper = fluid.layers.GreedyEmbeddingHelper(
        embedding_fn, paddle.to_tensor(np.array([0, 0], np.int64)),
        end_token=3)
    decoder = fluid.layers.BasicDecoder(cell, helper, output_fn=output_fn)
    init_states = paddle.to_tensor(np.zeros((2, D), np.float32))
    outputs, final_states, lengths = fluid.layers.dynamic_decode(
        decoder, inits=init_states, max_step_num=6, return_length=True)
    ids = outputs.sample_ids.numpy()
    assert ids.shape[0] == 2
    assert np.all(ids == 3)
    np.testing.assert_array_equal(lengths.numpy(), [1, 1])


def test_training_helper_teacher_forcing():
    rng = np.random.RandomState(5)
    B, T, D = 2, 4, 5
    seq = rng.randn(B, T, D).astype(np.float32)
    cell = fluid.layers.GRUCell(hidden_size=D)
    helper = fluid.layers.TrainingHelper(
        paddle.to_tensor(seq),
        paddle.to_tensor(np.array([4, 2], np.int64)))
    decoder = fluid.layers.BasicDecoder(cell, helper)
    outputs, _, lengths = fluid.layers.dynamic_decode(
        decoder, inits=paddle.to_tensor(np.zeros((B, D), np.float32)),
        max_step_num=10, return_length=True)
    assert outputs.cell_outputs.shape[0] == B
    np.testing.assert_array_equal(lengths.numpy(), [4, 2])


def test_continuous_value_model():
    x = np.array([[2.0, 1.0, 5.0, 6.0], [0.0, 3.0, 7.0, 8.0]], np.float32)
    cvm = np.ones((2, 2), np.float32)
    out = fluid.layers.continuous_value_model(
        paddle.to_tensor(x), paddle.to_tensor(cvm), use_cvm=True)
    o = out.numpy()
    np.testing.assert_allclose(o[:, 0], np.log(x[:, 0] + 1), atol=1e-6)
    np.testing.assert_allclose(o[:, 1],
                               np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
                               atol=1e-6)
    np.testing.assert_allclose(o[:, 2:], x[:, 2:])
    out2 = fluid.layers.continuous_value_model(
        paddle.to_tensor(x), paddle.to_tensor(cvm), use_cvm=False)
    np.testing.assert_allclose(out2.numpy(), x[:, 2:])


def test_similarity_focus_golden():
    """Mirror of similarity_focus_op.h: greedy row/col-exclusive argmax
    selection per indexed slice, mask broadcast over the axis dim."""
    rng = np.random.RandomState(6)
    x = rng.rand(2, 3, 4, 5).astype(np.float32)
    out = fluid.layers.similarity_focus(paddle.to_tensor(x), axis=1,
                                        indexes=[0, 2]).numpy()

    want = np.zeros_like(x)
    for n in range(2):
        for idx in (0, 2):
            sl = x[n, idx].copy()
            H, W = sl.shape
            rows = np.zeros(H, bool)
            cols = np.zeros(W, bool)
            order = np.argsort(-sl.reshape(-1), kind="stable")
            picked = 0
            for flat in order:
                r, c = flat // W, flat % W
                if rows[r] or cols[c]:
                    continue
                rows[r] = cols[c] = True
                want[n, :, r, c] = 1
                picked += 1
                if picked == min(H, W):
                    break
    np.testing.assert_array_equal(out, want)


def test_prroi_pool_exact_integral():
    """Bilinear interpolant of f(x, y) = x is exactly x, so each bin's
    precise integral average equals the bin's center x (same for y)."""
    H = W = 8
    xs = np.broadcast_to(np.arange(W, dtype=np.float32), (H, W))
    feat = np.stack([xs, xs.T])[None]            # [1, 2, H, W]: x and y
    rois = np.array([[1.0, 2.0, 5.0, 6.0]], np.float32)
    out = fluid.layers.prroi_pool(paddle.to_tensor(feat),
                                  paddle.to_tensor(rois), 1.0, 2, 2)
    o = out.numpy()[0]
    assert o.shape == (2, 2, 2)
    # channel 0 (= x): bins split [1,3],[3,5]; centers 2 and 4
    np.testing.assert_allclose(o[0], [[2, 4], [2, 4]], atol=1e-5)
    # channel 1 (= y): bins split [2,4],[4,6]; centers 3 and 5
    np.testing.assert_allclose(o[1], [[3, 3], [5, 5]], atol=1e-5)


def test_prroi_pool_constant_and_grad():
    feat = paddle.to_tensor(np.ones((1, 1, 6, 6), np.float32),
                            stop_gradient=False)
    rois = paddle.to_tensor(np.array([[0.5, 0.5, 4.5, 4.5]], np.float32))
    out = fluid.layers.prroi_pool(feat, rois, 1.0, 3, 3)
    np.testing.assert_allclose(out.numpy(), np.ones((1, 1, 3, 3)),
                               atol=1e-5)
    paddle.sum(out).backward()
    g = feat.grad.numpy()
    assert np.isfinite(g).all() and g.sum() > 0


def test_deformable_roi_pooling_no_trans_constant():
    feat = np.full((1, 2, 8, 8), 3.0, np.float32)
    rois = np.array([[1, 1, 6, 6]], np.float32)
    trans = np.zeros((1, 2, 2, 2), np.float32)
    out = fluid.layers.deformable_roi_pooling(
        paddle.to_tensor(feat), paddle.to_tensor(rois),
        paddle.to_tensor(trans), no_trans=True, pooled_height=2,
        pooled_width=2, sample_per_part=2)
    np.testing.assert_allclose(out.numpy(), np.full((1, 2, 2, 2), 3.0),
                               atol=1e-5)


def test_deformable_roi_pooling_offset_shifts():
    """A positive x-offset moves sampling right on an x-ramp feature."""
    H = W = 12
    xs = np.broadcast_to(np.arange(W, dtype=np.float32), (H, W))
    feat = xs[None, None]
    rois = np.array([[2, 2, 7, 7]], np.float32)
    z = np.zeros((1, 2, 1, 1), np.float32)
    off = z.copy()
    off[0, 0] = 1.0       # x offset, scaled by trans_std * roi_w
    base = fluid.layers.deformable_roi_pooling(
        paddle.to_tensor(feat), paddle.to_tensor(rois),
        paddle.to_tensor(z), pooled_height=1, pooled_width=1,
        sample_per_part=2, trans_std=0.1).numpy()
    shifted = fluid.layers.deformable_roi_pooling(
        paddle.to_tensor(feat), paddle.to_tensor(rois),
        paddle.to_tensor(off), pooled_height=1, pooled_width=1,
        sample_per_part=2, trans_std=0.1).numpy()
    roi_w = 6.0    # (round(7)+1 - round(2)) * scale
    np.testing.assert_allclose(shifted - base, 0.1 * roi_w, atol=1e-4)


def test_fluid_distribution_reexports():
    assert fluid.layers.Uniform is not None
    assert fluid.layers.Normal is not None
    assert fluid.layers.Categorical is not None
    assert fluid.layers.MultivariateNormalDiag is not None


def test_dynamic_decode_sticky_finished():
    """A row that emits end early must STAY finished even if a later step
    samples a non-end token (reference logical_or semantics)."""
    B, D, V = 2, 4, 6

    class FlipHelper(fluid.layers.DecodeHelper):
        def initialize(self):
            return (paddle.to_tensor(np.zeros((B, D), np.float32)),
                    paddle.to_tensor(np.zeros((B,), bool)))

        def sample(self, time, outputs, states):
            # row 0 emits end (id 3) ONLY at t==0, then non-end forever
            ids = np.full((B,), 1, np.int64)
            if time == 0:
                ids[0] = 3
            if time == 3:
                ids[:] = 3
            return paddle.to_tensor(ids)

        def next_inputs(self, time, outputs, states, sample_ids):
            fin = paddle.to_tensor(
                np.asarray(sample_ids.numpy()).reshape(-1) == 3)
            return fin, paddle.to_tensor(np.zeros((B, D), np.float32)), \
                states

    cell = fluid.layers.GRUCell(hidden_size=D)
    dec = fluid.layers.BasicDecoder(cell, FlipHelper())
    _, _, lengths = fluid.layers.dynamic_decode(
        dec, inits=paddle.to_tensor(np.zeros((B, D), np.float32)),
        max_step_num=8, return_length=True)
    # row 0 finished at step 0 (length 1); row 1 at step 3 (length 4);
    # without sticky finished row 0 would wrongly count 8 steps
    np.testing.assert_array_equal(lengths.numpy(), [1, 4])
