"""py_reader compat trio + generate_mask_labels (the last 4 fluid.layers
names; ref fluid/layers/io.py:561,732,843, detection.py:2748)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid


def _batched_reader(n_batches=5, bs=4):
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(n_batches):
            yield [(rng.rand(784).astype("float32"),
                    np.array([rng.randint(10)], "int64"))
                   for _ in range(bs)]
    return reader


def _build_net():
    img = fluid.layers.py_reader(capacity=8,
                                 shapes=[(-1, 1, 28, 28), (-1, 1)],
                                 dtypes=["float32", "int64"],
                                 use_double_buffer=False)
    x, lbl = fluid.layers.read_file(img)
    flat = fluid.layers.reshape(x, [-1, 784])
    logits = fluid.layers.fc(flat, 10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, lbl))
    return img, loss


class TestPyReader:
    def test_classic_loop_runs_verbatim(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                reader, loss = _build_net()
                reader.decorate_paddle_reader(_batched_reader(n_batches=5))
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                for _epoch in range(2):     # restartable across passes
                    reader.start()
                    losses = []
                    try:
                        while True:
                            lv, = exe.run(main, fetch_list=[loss])
                            losses.append(float(lv))
                    except fluid.core.EOFException:
                        reader.reset()
                    assert len(losses) == 5
                    assert all(np.isfinite(l) for l in losses)
        finally:
            paddle.disable_static()

    def test_sample_fields_reshaped_to_slot_shape(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=4, shapes=[(-1, 2, 3)], dtypes=["float32"],
                    use_double_buffer=False)
                x = fluid.layers.read_file(rd)
                y = fluid.layers.reduce_sum(x)

                def src():
                    yield [(np.arange(6, dtype="float32"),)]  # flat field
                rd.decorate_paddle_reader(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                val, = exe.run(main, fetch_list=[y])
                assert float(val) == 15.0
                with pytest.raises(fluid.core.EOFException):
                    while True:
                        exe.run(main, fetch_list=[y])
                rd.reset()
        finally:
            paddle.disable_static()

    def test_tensor_provider_and_double_buffer(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=4, shapes=[(-1, 3), (-1, 1)],
                    dtypes=["float32", "int64"], use_double_buffer=False)
                rd = fluid.layers.double_buffer(rd)
                assert rd.use_double_buffer
                a, b = fluid.layers.read_file(rd)
                out = fluid.layers.reduce_sum(a) + fluid.layers.cast(
                    fluid.layers.reduce_sum(b), "float32")

                def src():
                    for i in range(3):
                        yield (np.full((2, 3), i, "float32"),
                               np.full((2, 1), i, "int64"))
                rd.decorate_tensor_provider(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                vals = []
                try:
                    while True:
                        v, = exe.run(main, fetch_list=[out])
                        vals.append(float(v))
                except fluid.core.EOFException:
                    rd.reset()
                assert vals == [0.0, 8.0, 16.0]
        finally:
            paddle.disable_static()

    def test_double_buffer_uses_native_ring(self):
        # use_double_buffer=True stages batches through the C++ ring
        # when the native runtime is built
        from paddle_tpu import runtime
        if not runtime.is_available():
            pytest.skip("native runtime not built")
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=4, shapes=[(-1, 8)], dtypes=["float32"],
                    use_double_buffer=True)
                x = fluid.layers.read_file(rd)
                y = fluid.layers.reduce_sum(x)

                def src():
                    for i in range(6):
                        yield (np.full((2, 8), float(i), "float32"),)
                rd.decorate_batch_generator(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                assert rd._pass.ring is not None     # the C++ ring path
                vals = []
                try:
                    while True:
                        v, = exe.run(main, fetch_list=[y])
                        vals.append(float(v))
                except fluid.core.EOFException:
                    rd.reset()
                assert vals == [i * 16.0 for i in range(6)]
        finally:
            paddle.disable_static()

    def test_create_py_reader_by_data(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                img = fluid.layers.data("img_byd", [4], dtype="float32")
                lbl = fluid.layers.data("lbl_byd", [1], dtype="int64")
                rd = fluid.layers.create_py_reader_by_data(
                    capacity=4, feed_list=[img, lbl],
                    use_double_buffer=False)
                got = fluid.layers.read_file(rd)
                assert [t.name for t in got] == ["img_byd", "lbl_byd"]
                s = fluid.layers.reduce_sum(img)

                def src():
                    yield [(np.ones(4, "float32"), np.array([7], "int64"))]
                rd.decorate_paddle_reader(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                v, = exe.run(main, fetch_list=[s])
                assert float(v) == 4.0
                rd.reset()
        finally:
            paddle.disable_static()

    def test_source_error_beats_eof_when_consumer_blocked(self):
        # the filler closes the queue/ring on error too — a consumer that
        # was already waiting must see the error, not a clean EOF
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=2, shapes=[(-1, 2)], dtypes=["float32"],
                    use_double_buffer=False)
                x = fluid.layers.read_file(rd)
                y = fluid.layers.reduce_sum(x)

                def src():
                    import time
                    yield (np.ones((1, 2), "float32"),)
                    time.sleep(0.5)      # consumer blocks on the queue
                    raise ValueError("late source crash")
                rd.decorate_batch_generator(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                exe.run(main, fetch_list=[y])
                with pytest.raises(ValueError, match="late source crash"):
                    exe.run(main, fetch_list=[y])
        finally:
            paddle.disable_static()

    def test_by_data_preserves_unknown_dims(self):
        # fluid.data with -1 non-batch dims: samples keep their real size
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                seq = fluid.layers.data("seq_byd", [-1, 8],
                                        dtype="float32",
                                        append_batch_size=True)
                rd = fluid.layers.create_py_reader_by_data(
                    capacity=2, feed_list=[seq], use_double_buffer=False)
                s = fluid.layers.reduce_sum(seq)

                def src():
                    yield [(np.ones((5, 8), "float32"),)]
                rd.decorate_paddle_reader(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                v, = exe.run(main, fetch_list=[s])
                assert float(v) == 40.0
                rd.reset()
        finally:
            paddle.disable_static()

    def test_unstarted_reader_slot_raises_not_silent_zeros(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=2, shapes=[(-1, 2)], dtypes=["float32"],
                    use_double_buffer=False)
                x = fluid.layers.read_file(rd)
                y = fluid.layers.reduce_sum(x)
                rd.decorate_batch_generator(
                    lambda: iter([(np.ones((1, 2), "float32"),)]))
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                with pytest.raises(RuntimeError, match="not started"):
                    exe.run(main, fetch_list=[y])   # forgot rd.start()
        finally:
            paddle.disable_static()

    def test_ownership_scoped_per_program(self):
        # train and eval programs each declare fluid.data('shared_img')
        # with their own reader — the hook must resolve per program
        paddle.enable_static()
        try:
            readers, progs, losses = [], [], []
            for fill in (1.0, 2.0):
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    v = fluid.layers.data("shared_img", [2],
                                          dtype="float32")
                    rd = fluid.layers.create_py_reader_by_data(
                        capacity=2, feed_list=[v],
                        use_double_buffer=False)
                    rd.decorate_batch_generator(
                        lambda fill=fill: iter(
                            [(np.full((1, 2), fill, "float32"),)]))
                    losses.append(fluid.layers.reduce_sum(v))
                    progs.append(main)
                    readers.append(rd)
                    fluid.Executor(fluid.CPUPlace()).run(startup)
            exe = fluid.Executor(fluid.CPUPlace())
            readers[0].start()
            readers[1].start()
            v0, = exe.run(progs[0], fetch_list=[losses[0]])
            v1, = exe.run(progs[1], fetch_list=[losses[1]])
            assert float(v0) == 2.0     # batch of 1.0s from reader 0
            assert float(v1) == 4.0     # batch of 2.0s from reader 1
            readers[0].reset()
            readers[1].reset()
        finally:
            paddle.disable_static()

    def test_partial_manual_feed_rejected(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=2, shapes=[(-1, 2), (-1, 1)],
                    dtypes=["float32", "float32"], use_double_buffer=False)
                a, b = fluid.layers.read_file(rd)
                y = fluid.layers.reduce_sum(a) + fluid.layers.reduce_sum(b)

                def src():
                    yield (np.ones((1, 2), "float32"),
                           np.ones((1, 1), "float32"))
                rd.decorate_batch_generator(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                with pytest.raises(RuntimeError, match="feed all"):
                    exe.run(main,
                            feed={rd._slots[0].name:
                                  np.zeros((1, 2), "float32")},
                            fetch_list=[y])
                rd.reset()
        finally:
            paddle.disable_static()

    def test_source_error_surfaces_on_consumer(self):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                rd = fluid.layers.py_reader(
                    capacity=2, shapes=[(-1, 2)], dtypes=["float32"],
                    use_double_buffer=False)
                x = fluid.layers.read_file(rd)
                y = fluid.layers.reduce_sum(x)

                def src():
                    raise RuntimeError("boom in source")
                    yield  # pragma: no cover
                rd.decorate_batch_generator(src)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rd.start()
                import time
                time.sleep(0.3)   # let the filler thread hit the error
                with pytest.raises(RuntimeError, match="boom in source"):
                    exe.run(main, fetch_list=[y])
        finally:
            paddle.disable_static()


class TestGenerateMaskLabels:
    """Golden tests against hand-computable rectangle polygons
    (ref generate_mask_labels_op.cc / mask_util.cc)."""

    def _run(self, rois, labels, polys, gt_cls, crowd=None, K=3, M=4,
             scale=1.0):
        crowd = crowd if crowd is not None else [0] * len(gt_cls)
        return fluid.layers.generate_mask_labels(
            im_info=np.array([[32.0, 32.0, scale]], "float32"),
            gt_classes=[np.asarray(gt_cls)],
            is_crowd=[np.asarray(crowd)],
            gt_segms=[polys],
            rois=[np.asarray(rois, "float32")],
            labels_int32=[np.asarray(labels, "int32")],
            num_classes=K, resolution=M)

    def test_full_rectangle_gives_all_ones_in_class_slice(self):
        # one gt (class 1) whose polygon exactly covers the single fg roi
        rect = [0.0, 0.0, 8.0, 0.0, 8.0, 8.0, 0.0, 8.0]
        mask_rois, has_mask, mask_int32, lod = self._run(
            rois=[[0, 0, 8, 8]], labels=[1], polys=[[rect]], gt_cls=[1],
            K=3, M=4)
        assert mask_rois.shape == (1, 4)
        assert has_mask.tolist() == [[0]]
        assert lod.tolist() == [1]
        m = mask_int32.reshape(3, 4, 4)
        assert (m[0] == -1).all()               # background slice ignored
        assert (m[1] == 1).all()                # fg class slice: full mask
        assert (m[2] == -1).all()
    def test_half_rectangle(self):
        # polygon covers the left half of the roi -> left half columns set
        rect = [0.0, 0.0, 4.0, 0.0, 4.0, 8.0, 0.0, 8.0]
        _, _, mask_int32, _ = self._run(
            rois=[[0, 0, 8, 8]], labels=[2], polys=[[rect]], gt_cls=[2],
            K=3, M=4)
        m = mask_int32.reshape(3, 4, 4)[2]
        assert (m[:, :2] == 1).all() and (m[:, 2:] == 0).all()

    def test_best_overlap_gt_chosen_and_crowd_skipped(self):
        # two gts; roi overlaps gt1 (right side). gt0 is crowd -> skipped,
        # so only gt1 participates regardless of overlap.
        left = [0.0, 0.0, 8.0, 0.0, 8.0, 16.0, 0.0, 16.0]
        right = [8.0, 0.0, 16.0, 0.0, 16.0, 16.0, 8.0, 16.0]
        _, _, mask_int32, _ = self._run(
            rois=[[8, 0, 16, 16]], labels=[1],
            polys=[[left], [right]], gt_cls=[1, 1], crowd=[1, 0],
            K=2, M=4)
        m = mask_int32.reshape(2, 4, 4)[1]
        assert (m == 1).all()   # right polygon fully covers the roi

    def test_no_fg_falls_back_to_ignore_mask(self):
        rect = [0.0, 0.0, 8.0, 0.0, 8.0, 8.0, 0.0, 8.0]
        mask_rois, has_mask, mask_int32, lod = self._run(
            rois=[[0, 0, 8, 8], [8, 8, 16, 16]], labels=[0, 0],
            polys=[[rect]], gt_cls=[1], K=3, M=4)
        assert mask_rois.shape == (1, 4)
        assert (mask_int32 == -1).all()
        assert lod.tolist() == [1]

    def test_multi_image_lod(self):
        rect = [0.0, 0.0, 8.0, 0.0, 8.0, 8.0, 0.0, 8.0]
        out = fluid.layers.generate_mask_labels(
            im_info=np.array([[32, 32, 1.0], [32, 32, 1.0]], "float32"),
            gt_classes=[np.array([1]), np.array([1])],
            is_crowd=[np.array([0]), np.array([0])],
            gt_segms=[[[rect]], [[rect]]],
            rois=[np.array([[0, 0, 8, 8]], "float32"),
                  np.array([[0, 0, 8, 8], [1, 1, 7, 7]], "float32")],
            labels_int32=[np.array([1], "int32"),
                          np.array([1, 1], "int32")],
            num_classes=2, resolution=4)
        mask_rois, has_mask, mask_int32, lod = out
        assert lod.tolist() == [1, 2]
        assert mask_rois.shape == (3, 4)
        assert mask_int32.shape == (3, 2 * 16)
