"""Legacy paddle.dataset reader creators, paddle.batch, paddle.hub local
source (ref python/paddle/dataset/, batch.py, hub.py)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle


def test_dataset_mnist_reader_schema():
    r = paddle.dataset.mnist.train()
    img, label = next(iter(r()))
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert isinstance(label, int) and 0 <= label <= 9


def test_dataset_uci_housing_reader():
    r = paddle.dataset.uci_housing.train()
    x, y = next(iter(r()))
    assert x.shape == (13,) and y.shape == (1,)


def test_dataset_cifar_reader():
    r = paddle.dataset.cifar.train10()
    img, label = next(iter(r()))
    assert img.shape == (3072,) and 0.0 <= img.min() <= img.max() <= 1.0
    assert 0 <= label <= 9


def test_dataset_imdb_reader_and_word_dict():
    wd = paddle.dataset.imdb.word_dict()
    assert len(wd) > 0
    ids, label = next(iter(paddle.dataset.imdb.train(wd)()))
    assert isinstance(ids, list) and label in (0, 1)


def test_paddle_batch_composes_with_dataset():
    batches = list(paddle.batch(
        paddle.reader.firstn(paddle.dataset.uci_housing.train(), 10), 4)())
    assert [len(b) for b in batches] == [4, 4, 2]
    xs = np.stack([x for x, _ in batches[0]])
    assert xs.shape == (4, 13)


def test_hub_local_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        import paddle_tpu as paddle

        def tiny_mlp(hidden=4):
            \"\"\"A tiny MLP entrypoint.\"\"\"
            return paddle.nn.Sequential(
                paddle.nn.Linear(2, hidden), paddle.nn.ReLU(),
                paddle.nn.Linear(hidden, 1))

        def _private():
            pass
    """))
    names = paddle.hub.list(str(tmp_path))
    assert "tiny_mlp" in names and "_private" not in names
    assert "tiny MLP" in paddle.hub.help(str(tmp_path), "tiny_mlp")
    net = paddle.hub.load(str(tmp_path), "tiny_mlp", hidden=8)
    out = net(paddle.to_tensor(np.ones((3, 2), np.float32)))
    assert tuple(out.shape) == (3, 1)


def test_hub_remote_sources_raise():
    with pytest.raises(RuntimeError):
        paddle.hub.list("some/repo", source="github")
