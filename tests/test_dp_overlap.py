"""Overlap-scheduled bucketed gradient reduction (distributed/reducer.py)
on the 8-device virtual CPU mesh, plus the tape's grad-ready plumbing and
the fused bucket-consuming optimizer step.

Models the reference's reducer unittests (ref: test_imperative_data_parallel
/ reducer.cc bucket assignment) with the parity contract from PyTorch-DDP
style overlap: the overlapped-bucketed schedule must train bit-for-bit like
the naive sync-at-end schedule."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import reducer as reducer_mod
from paddle_tpu.distributed.reducer import (
    Reducer, DeviceMeshAllReduce, EagerProcessTransport, build_buckets)


def _mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("dp",))


def _mlp(widths=(16, 32, 16, 4)):
    paddle.seed(7)
    layers = []
    for a, b in zip(widths[:-1], widths[1:]):
        layers += [nn.Linear(a, b), nn.Tanh()]
    return nn.Sequential(*layers[:-1])


# ------------------------------------------------------------------ buckets

def test_bucket_build_reverse_order_and_cap():
    net = _mlp()
    params = list(net.parameters())
    # huge cap: ONE bucket holding every param in reverse registration
    # order (backward completes grads roughly back-to-front)
    (b,) = build_buckets(params, bucket_size_mb=1e9)
    assert [id(p) for p in b.params] == [id(p) for p in reversed(params)]
    assert b.numel == sum(int(np.prod(p.shape)) if p.shape else 1
                          for p in params)
    # offsets tile the flat exactly (uneven tail included)
    assert b.offsets[0] == 0
    for off, n, nxt in zip(b.offsets, b.numels, b.offsets[1:]):
        assert off + n == nxt


def test_bucket_size_smaller_than_one_param():
    net = _mlp()
    params = list(net.parameters())
    buckets = build_buckets(params, bucket_size_mb=1e-9)  # < any param
    # every param gets a bucket of its own, order still reversed
    assert len(buckets) == len(params)
    assert all(len(b.params) == 1 for b in buckets)
    assert [id(b.params[0]) for b in buckets] == \
        [id(p) for p in reversed(params)]


def test_bucket_dtype_split():
    p1 = paddle.ones([4], dtype="float32")
    p2 = paddle.ones([4], dtype="float16")
    p1.stop_gradient = p2.stop_gradient = False
    assert p1.dtype != p2.dtype
    buckets = build_buckets([p1, p2], bucket_size_mb=1e9)
    assert len(buckets) == 2  # mixed dtypes never share a flat bucket


# ---------------------------------------------------- tape hook plumbing

def test_grad_ready_hooks_fire_mid_backward():
    """A late layer's param hook must fire while EARLIER layers' tape
    nodes are still unconsumed — the property the overlap schedule rides
    (the collective launches while backward keeps walking)."""
    net = _mlp((8, 8, 8, 8))
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))
    h1 = net[0](x)                       # first Linear's output
    out = net[2](paddle.tanh(h1))
    for lyr in (net[4],):
        out = lyr(paddle.tanh(out))
    first_node = h1._node
    seen = {}

    def hook(g):
        # the first Linear's node has not been processed yet: its vjp
        # closure is still alive mid-walk (backward() frees it on use)
        seen["first_node_alive"] = first_node.vjp_fn is not None
        return None

    net[4].weight.register_hook(hook)
    out.mean().backward()
    assert seen["first_node_alive"] is True
    # and the walk then completed normally
    assert net[0].weight.grad is not None


def test_backward_end_callbacks_run_once_and_clear():
    from paddle_tpu.autograd import tape
    calls = []
    w = paddle.ones([3])
    w.stop_gradient = False

    def make_loss():
        return (w * w).sum()

    def hook(g):
        tape.queue_backward_end_callback(lambda: calls.append(1))
        return None

    h = w.register_hook(hook)
    make_loss().backward()
    assert calls == [1]
    make_loss().backward()
    assert calls == [1, 1]               # re-queued per backward, not stale
    h.remove()


# ------------------------------------------------- parity on the host mesh

def _train(mode, steps=10, bucket_mb=0.002, widths=(16, 32, 16, 4),
           fuse=True):
    net = _mlp(widths)
    kwargs = dict(mesh=_mesh8())
    if mode == "overlap":
        kwargs.update(bucket_size_mb=bucket_mb, overlap=True,
                      fuse_into_step=fuse)
    elif mode == "sync":
        kwargs.update(bucket_size_mb=1e9, overlap=False)
    dp = dist.DataParallel(net, **kwargs) if mode != "plain" else None
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters(),
                                 weight_decay=0.01)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(8, widths[0]).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, widths[-1]).astype(np.float32))
    model = dp if dp is not None else net
    for _ in range(steps):
        loss = paddle.nn.functional.mse_loss(model(x), y)
        loss.backward()
        if mode == "overlap" and fuse:
            dp.step_fused(opt)
        else:
            opt.step()
        opt.clear_grad()
    n_buckets = len(dp.reducer.buckets) if dp is not None else 0
    return [np.asarray(p.numpy()) for p in net.parameters()], n_buckets


def test_overlap_matches_sync_and_plain_10_steps():
    """The core parity contract: overlapped-bucketed DP (fused bucket
    step) == naive sync-at-end DP (write-back + plain step) == plain
    single-process training, to 1e-6 after 10 steps."""
    reducer_mod.reset_reducer_stats()
    ref, _ = _train("plain")
    sync, _ = _train("sync")
    stats0 = reducer_mod.reducer_stats()
    ov, n_buckets = _train("overlap")
    stats = reducer_mod.reducer_stats()
    assert n_buckets > 1                  # the bucketed path was exercised
    for a, b in zip(sync, ref):
        np.testing.assert_allclose(a, b, atol=1e-6)
    for a, b in zip(ov, ref):
        np.testing.assert_allclose(a, b, atol=1e-6)
    # exactly one collective launch per bucket per step, all from hooks
    launched = stats["collectives_launched"] - stats0["collectives_launched"]
    assert launched == n_buckets * 10
    assert stats["overlap_launches"] > stats0["overlap_launches"]


def test_overlap_writeback_without_fused_step():
    """overlap=True without fuse_into_step: reduced grads land back in
    p.grad and a PLAIN opt.step() trains identically."""
    ref, _ = _train("plain")
    ov, _ = _train("overlap", fuse=False)
    for a, b in zip(ov, ref):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_gradless_param_contributes_zeros():
    """A param with no grad path still occupies its bucket slot (zeros),
    buckets still launch exactly once, and used params train exactly like
    the no-DP run — the deterministic-membership contract."""

    class Partial(nn.Layer):
        def __init__(self):
            super().__init__()
            self.used = nn.Linear(8, 4)
            self.unused = nn.Linear(8, 4)   # never in the loss

        def forward(self, x):
            return self.used(x)

    def run(dp_mode):
        paddle.seed(11)
        net = Partial()
        dp = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9,
                               overlap=True) if dp_mode else None
        rng = np.random.RandomState(5)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        loss = (net(x) ** 2).mean() if dp is None \
            else (dp(x) ** 2).mean()
        loss.backward()
        return net

    reducer_mod.reset_reducer_stats()
    net_dp = run(True)
    stats = reducer_mod.reducer_stats()
    net_ref = run(False)
    assert stats["zero_filled_params"] == 2      # unused weight + bias
    assert stats["collectives_launched"] == 1
    np.testing.assert_allclose(
        np.asarray(net_dp.used.weight.grad.numpy()),
        np.asarray(net_ref.used.weight.grad.numpy()), atol=1e-6)
    # the grad-less param adopted the (all-zero) reduced slice
    g = net_dp.unused.weight.grad
    assert g is not None and not np.asarray(g.numpy()).any()


def test_no_sync_suppresses_collectives():
    reducer_mod.reset_reducer_stats()
    net = _mlp()
    dp = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 16).astype(np.float32))
    with dp.no_sync():
        (dp(x) ** 2).mean().backward()
    assert reducer_mod.reducer_stats()["collectives_launched"] == 0
    (dp(x) ** 2).mean().backward()       # sync resumes after the context
    assert reducer_mod.reducer_stats()["collectives_launched"] == 1


# ------------------------------------------------- subset process groups

class _FakeRows:
    """Monkeypatched collective backend: pretends to be a 4-process world
    whose row j is (local + j)."""

    def __init__(self, nproc):
        self.nproc = nproc

    def rows(self, value):
        v = np.asarray(value)
        return np.stack([v + j for j in range(self.nproc)])


def test_subset_group_maps_group_ranks(monkeypatch):
    """EagerProcessTransport over a subset group: only MEMBER rows enter
    the reduction (mapped through group ranks), non-members keep local
    grads (transport returns None)."""
    from paddle_tpu.distributed import collective
    fake = _FakeRows(4)
    monkeypatch.setattr(collective, "_process_count", lambda: 4)
    monkeypatch.setattr(collective, "_eager_rows",
                        lambda v, **kw: fake.rows(v))

    member_group = collective.Group(rank=0, nranks=2, id=7, ranks=[1, 3])
    tr = EagerProcessTransport(member_group)
    assert tr.nranks == 2
    flat = jnp.arange(4.0)
    out = tr.all_reduce_flat(flat)
    # member rows are global ranks 1 and 3: (flat+1) + (flat+3)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(flat) * 2 + 4)

    non_member = collective.Group(rank=-1, nranks=2, id=8, ranks=[1, 3])
    tr2 = EagerProcessTransport(non_member)
    assert tr2.all_reduce_flat(flat) is None


def test_reducer_subset_non_member_keeps_local_grads(monkeypatch):
    from paddle_tpu.distributed import collective
    fake = _FakeRows(4)
    monkeypatch.setattr(collective, "_process_count", lambda: 4)
    monkeypatch.setattr(collective, "_eager_rows",
                        lambda v, **kw: fake.rows(v))
    net = _mlp((8, 8, 4))
    group = collective.Group(rank=-1, nranks=2, id=9, ranks=[1, 3])
    red = Reducer(net.parameters(), bucket_size_mb=1e9,
                  transport=EagerProcessTransport(group)).install_hooks()
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(4, 8).astype(np.float32))
    (net(x) ** 2).mean().backward()
    # non-member: pop_reduced empty, local grads untouched by any scale
    assert red.pop_reduced() is None
    assert net[0].weight.grad is not None


# ------------------------------------------------- fused bucket step unit

def test_step_from_buckets_matches_manual_unbucket():
    paddle.seed(3)
    net_a = _mlp((8, 8, 4))
    paddle.seed(3)
    net_b = _mlp((8, 8, 4))
    pa, pb = list(net_a.parameters()), list(net_b.parameters())
    rng = np.random.RandomState(0)
    grads = [rng.randn(*p.shape).astype(np.float32) * 8 for p in pa]

    # bucket layout over net_a: two flats, reverse order, scale 1/8
    buckets = build_buckets(pa, bucket_size_mb=1e-9)
    flats, layout = [], []
    for b in buckets:
        by_id = {id(p): g for p, g in zip(pa, grads)}
        flats.append(jnp.concatenate(
            [jnp.asarray(by_id[id(p)]).reshape(-1) for p in b.params]))
        for p, off, n, shape in zip(b.params, b.offsets, b.numels,
                                    b.shapes):
            layout.append((p, len(flats) - 1, off, n, shape))
    opt_a = paddle.optimizer.AdamW(1e-2, parameters=pa, weight_decay=0.01)
    opt_a.step_from_buckets(flats, layout, scale=1.0 / 8)

    opt_b = paddle.optimizer.AdamW(1e-2, parameters=pb, weight_decay=0.01)
    for p, g in zip(pb, grads):
        p.grad = paddle.to_tensor(g / 8)
    opt_b.step()

    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a.numpy()),
                                   np.asarray(b.numpy()), atol=1e-6)


def test_step_from_buckets_extra_direct_grads():
    """Params with a direct .grad but no bucket slot ride the same fused
    call (subset non-member buckets, late-registered params)."""
    paddle.seed(5)
    net = _mlp((8, 8, 4))
    params = list(net.parameters())
    in_bucket, extra = params[:2], params[2:]
    rng = np.random.RandomState(2)
    buckets = build_buckets(in_bucket, bucket_size_mb=1e9)
    flats, layout = [], []
    for b in buckets:
        gs = [rng.randn(*p.shape).astype(np.float32) for p in b.params]
        flats.append(jnp.concatenate([jnp.asarray(g).reshape(-1)
                                      for g in gs]))
        for p, off, n, shape in zip(b.params, b.offsets, b.numels,
                                    b.shapes):
            layout.append((p, len(flats) - 1, off, n, shape))
    before = [np.asarray(p.numpy()) for p in extra]
    for p in extra:
        p.grad = paddle.to_tensor(
            rng.randn(*p.shape).astype(np.float32))
    opt = paddle.optimizer.Momentum(0.1, parameters=params)
    opt.step_from_buckets(flats, layout, scale=1.0)
    for p, b0 in zip(extra, before):
        assert not np.allclose(np.asarray(p.numpy()), b0)


# --------------------------------------------- review-finding regressions

def test_reducer_recovers_after_aborted_backward():
    """An exception mid-backward drops the finalize callback without
    running it; the NEXT backward must re-queue and sync normally instead
    of silently never reducing again."""
    reducer_mod.reset_reducer_stats()
    net = _mlp((8, 8, 4))
    dp = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))

    boom = {"on": True}

    def bad_hook(g):
        if boom["on"]:
            raise RuntimeError("injected hook failure")
        return None

    h = net[0].bias.register_hook(bad_hook)
    with pytest.raises(RuntimeError, match="injected"):
        (dp(x) ** 2).mean().backward()
    boom["on"] = False
    (dp(x) ** 2).mean().backward()       # must sync again
    assert reducer_mod.reducer_stats()["collectives_launched"] >= 1
    assert net[0].weight.grad is not None
    h.remove()


def test_paddle_grad_does_not_clobber_bucket_grads():
    """paddle.grad (watch mode) between backward and step must not
    trigger the reducer — a bucket finalize there would zero-fill and
    overwrite every other member's synced grad (gradient-penalty
    recipes)."""
    reducer_mod.reset_reducer_stats()
    net = _mlp((8, 8, 4))
    dp = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))
    (dp(x) ** 2).mean().backward()
    synced = np.asarray(net[2].weight.grad.numpy())
    launched = reducer_mod.reducer_stats()["collectives_launched"]

    w = net[0].weight
    (g,) = paddle.grad((dp(x) ** 2).mean(), [w], retain_graph=False)
    assert g is not None
    # no new collective, and the other params' grads are untouched
    assert reducer_mod.reducer_stats()["collectives_launched"] == launched
    np.testing.assert_array_equal(
        np.asarray(net[2].weight.grad.numpy()), synced)


def test_prefetch_passes_non_numeric_leaves_through():
    from paddle_tpu import io
    batches = [{"x": np.ones((2, 4), np.float32), "id": "sample_%d" % i,
                "n": 3} for i in range(3)]
    out = list(io.prefetch_to_device(batches))
    assert [b["id"] for b in out] == ["sample_0", "sample_1", "sample_2"]
    assert all(b["n"] == 3 and isinstance(b["n"], int) for b in out)
    assert all(isinstance(b["x"], jax.Array) for b in out)


def test_nested_backward_in_hook_does_not_drain_outer_finalize():
    """A grad hook running paddle.grad on an unrelated graph must not
    drain the OUTER pass's queued reducer finalize mid-walk (it would
    reduce half-filled buckets and zero already-contributed grads)."""
    reducer_mod.reset_reducer_stats()
    net = _mlp((8, 8, 4))
    dp = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))

    def nested_query(g):
        w = paddle.to_tensor(np.ones(3, np.float32))
        w.stop_gradient = False
        (gw,) = paddle.grad((w * w).sum(), [w])
        assert gw is not None
        return None

    h = net[2].weight.register_hook(nested_query)
    (dp(x) ** 2).mean().backward()
    h.remove()
    stats = reducer_mod.reducer_stats()
    assert stats["collectives_launched"] == 1        # ONE finalize, at end
    assert stats["zero_filled_params"] == 0
    g = np.asarray(net[2].weight.grad.numpy())
    assert np.abs(g).sum() > 0                       # not zero-clobbered


def test_step_from_buckets_eager_fallback_keeps_raw_values(monkeypatch):
    """With the fused step disabled, the unbucketed eager fallback must
    leave p.value a raw jax array (not a Tensor) and match the fused
    result."""
    import os
    monkeypatch.setenv("PADDLE_TPU_FUSED_STEP", "0")
    paddle.seed(9)
    net = _mlp((8, 8, 4))
    params = list(net.parameters())
    rng = np.random.RandomState(1)
    buckets = build_buckets(params, bucket_size_mb=1e9)
    flats, layout = [], []
    for b in buckets:
        gs = [rng.randn(*p.shape).astype(np.float32) for p in b.params]
        flats.append(jnp.concatenate([jnp.asarray(g).reshape(-1)
                                      for g in gs]))
        for p, off, n, shape in zip(b.params, b.offsets, b.numels,
                                    b.shapes):
            layout.append((p, len(flats) - 1, off, n, shape))
    opt = paddle.optimizer.Momentum(0.1, parameters=params)
    opt.step_from_buckets(flats, layout, scale=0.5)
    from paddle_tpu.tensor.tensor import Tensor
    for p in params:
        assert not isinstance(p.value, Tensor), type(p.value)
        assert isinstance(p.value, jax.Array)


def test_step_from_buckets_permanent_fallback_on_trace_failure(monkeypatch):
    paddle.seed(9)
    net = _mlp((8, 8, 4))
    params = list(net.parameters())
    opt = paddle.optimizer.Momentum(0.1, parameters=params)

    def boom(*a, **k):
        raise ValueError("untraceable")

    monkeypatch.setattr(opt, "_step_from_buckets_fused", boom)
    buckets = build_buckets(params, bucket_size_mb=1e9)
    rng = np.random.RandomState(1)
    flats, layout = [], []
    for b in buckets:
        gs = [rng.randn(*p.shape).astype(np.float32) for p in b.params]
        flats.append(jnp.concatenate([jnp.asarray(g).reshape(-1)
                                      for g in gs]))
        for p, off, n, shape in zip(b.params, b.offsets, b.numels,
                                    b.shapes):
            layout.append((p, len(flats) - 1, off, n, shape))
    before = [np.asarray(p.numpy()) for p in params]
    opt.step_from_buckets(flats, layout, scale=1.0)
    assert opt._fused_supported is False       # permanent, like step()
    for p, b0 in zip(params, before):
        assert not np.allclose(np.asarray(p.numpy()), b0)


def test_rewrap_detaches_previous_reducer():
    """Re-wrapping the same layers (checkpoint reload pattern) must not
    stack reducers — the collective sequence would double."""
    reducer_mod.reset_reducer_stats()
    net = _mlp((8, 8, 4))
    dp1 = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9)
    dp2 = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))
    (dp2(x) ** 2).mean().backward()
    assert reducer_mod.reducer_stats()["collectives_launched"] == 1
    assert dp1.reducer is not dp2.reducer


def test_fuse_into_step_unconsumed_reduction_warns():
    net = _mlp((8, 8, 4))
    dp = dist.DataParallel(net, mesh=_mesh8(), bucket_size_mb=1e9,
                           fuse_into_step=True)
    opt = paddle.optimizer.Momentum(0.1, parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))
    (dp(x) ** 2).mean().backward()
    opt.step()                      # WRONG call for fuse mode — no pop
    opt.clear_grad()
    with pytest.warns(RuntimeWarning, match="step_fused"):
        (dp(x) ** 2).mean().backward()
