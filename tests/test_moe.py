"""Expert-parallel MoE: all-to-all dispatch parity vs dense routing,
differentiability, load-balance aux, capacity drops (driver spec's 'ep'
axis; the reference line grows this as incubate moe with NCCL alltoall)."""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import pytest

from paddle_tpu.parallel import moe

# model-level heavyweight suite: full train steps on the CPU mesh —
# runs in the slow tier, outside the tier-1 wall-clock budget
pytestmark = pytest.mark.slow


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("ep",))


def _sharded_apply(mesh, params, x, capacity_factor, E):
    pspecs = {"gate_w": P(), "w1": P("ep"), "b1": P("ep"),
              "w2": P("ep"), "b2": P("ep")}
    fn = shard_map(
        functools.partial(moe.moe_ffn, axis_name="ep",
                          capacity_factor=capacity_factor, n_experts=E),
        mesh=mesh,
        in_specs=(P("ep"), pspecs),
        out_specs=(P("ep"), P()),
        check_vma=False)
    return fn(x, params)


def test_moe_matches_dense_reference_no_drops():
    mesh = _mesh()
    E, H, F = 8, 16, 32
    rng = jax.random.PRNGKey(0)
    params = moe.init_moe_params(rng, E, H, F)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, H), jnp.float32)

    # capacity_factor=E => no token can overflow its expert buffer
    got, aux = _sharded_apply(mesh, params, x, capacity_factor=float(E),
                              E=E)
    want = moe.moe_ffn_dense_reference(x, params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)
    assert 0.5 < float(aux) < float(E)   # ~1 when perfectly balanced


def test_moe_capacity_drops_zero_not_garbage():
    mesh = _mesh()
    E, H, F = 8, 8, 16
    params = moe.init_moe_params(jax.random.PRNGKey(2), E, H, F)
    # force collisions: tiny capacity
    x = jax.random.normal(jax.random.PRNGKey(3), (64, H), jnp.float32)
    got, _ = _sharded_apply(mesh, params, x, capacity_factor=0.25, E=E)
    want = moe.moe_ffn_dense_reference(x, params)
    g = np.asarray(got)
    w = np.asarray(want)
    # every row either matches the reference or was dropped to exact zero
    row_zero = (np.abs(g).max(axis=1) == 0)
    row_match = np.abs(g - w).max(axis=1) < 2e-5
    assert (row_zero | row_match).all()
    assert row_zero.any()                # capacity really binds here


def test_moe_differentiable_and_trains():
    mesh = _mesh()
    E, H, F = 8, 8, 16
    params = moe.init_moe_params(jax.random.PRNGKey(4), E, H, F)
    x = jax.random.normal(jax.random.PRNGKey(5), (32, H), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(6), (32, H), jnp.float32)

    def loss_fn(p):
        out, aux = _sharded_apply(mesh, p, x, capacity_factor=4.0, E=E)
        return jnp.mean((out - y) ** 2) + 0.01 * aux

    l0 = float(loss_fn(params))
    grads = jax.grad(loss_fn)(params)
    gnorms = {k: float(jnp.linalg.norm(g)) for k, g in grads.items()}
    assert gnorms["gate_w"] > 0 and gnorms["w1"] > 0 and gnorms["w2"] > 0
    p2 = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, params, grads)
    for _ in range(10):
        grads = jax.grad(loss_fn)(p2)
        p2 = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g, p2, grads)
    assert float(loss_fn(p2)) < l0
