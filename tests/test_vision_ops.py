"""paddle.vision.ops: deform_conv2d vs a naive numpy golden, YOLO box
decode invariants, yolo_loss behavior, host image io; plus the
distribution long-tail (MultivariateNormalDiag, sampling_id)."""
import io
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _naive_deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                         dilation=1, dg=1, groups=1, mask=None):
    """Straight-loop reference implementation."""
    N, Cin, H, W = x.shape
    Cout, Cin_g, Kh, Kw = weight.shape
    sh = sw = stride
    ph = pw = padding
    dh = dw = dilation
    Ho = (H + 2 * ph - (dh * (Kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (Kw - 1) + 1)) // sw + 1
    K = Kh * Kw
    off = offset.reshape(N, dg, K, 2, Ho, Wo)
    msk = (mask.reshape(N, dg, K, Ho, Wo) if mask is not None
           else np.ones((N, dg, K, Ho, Wo), np.float32))
    out = np.zeros((N, Cout, Ho, Wo), np.float32)
    cg = Cin // dg
    cpg = Cin // groups       # channels per conv group

    def bil(img, y, x_):
        if y <= -1 or y >= img.shape[0] or x_ <= -1 or x_ >= img.shape[1]:
            return 0.0
        y0, x0 = int(np.floor(y)), int(np.floor(x_))
        wy, wx = y - y0, x_ - x0
        v = 0.0
        for ddy, ddx, w_ in ((0, 0, (1 - wy) * (1 - wx)),
                             (0, 1, (1 - wy) * wx),
                             (1, 0, wy * (1 - wx)), (1, 1, wy * wx)):
            yy, xx = y0 + ddy, x0 + ddx
            if 0 <= yy < img.shape[0] and 0 <= xx < img.shape[1]:
                v += w_ * img[yy, xx]
        return v

    for n in range(N):
        for m in range(Cout):
            g = m // (Cout // groups)
            for ho in range(Ho):
                for wo in range(Wo):
                    acc = 0.0
                    for ci in range(Cin_g):
                        c = g * cpg + ci
                        dgi = c // cg
                        for ki in range(Kh):
                            for kj in range(Kw):
                                k = ki * Kw + kj
                                y = (ho * sh - ph + ki * dh
                                     + off[n, dgi, k, 0, ho, wo])
                                x_ = (wo * sw - pw + kj * dw
                                      + off[n, dgi, k, 1, ho, wo])
                                acc += (weight[m, ci, ki, kj]
                                        * bil(x[n, c], y, x_)
                                        * msk[n, dgi, k, ho, wo])
                    out[n, m, ho, wo] = acc
            if bias is not None:
                out[n, m] += bias[m]
    return out


class TestDeformConv:
    @pytest.mark.parametrize("use_mask", [False, True])
    def test_vs_naive(self, use_mask):
        rng = np.random.RandomState(0)
        N, Cin, H, W, Cout, Kh = 1, 2, 5, 5, 3, 3
        x = rng.randn(N, Cin, H, W).astype("float32")
        w = rng.randn(Cout, Cin, Kh, Kh).astype("float32") * 0.3
        b = rng.randn(Cout).astype("float32")
        off = rng.randn(N, 2 * Kh * Kh, H, W).astype("float32") * 0.5
        m = (rng.rand(N, Kh * Kh, H, W).astype("float32")
             if use_mask else None)
        ours = V.deform_conv2d(
            paddle.to_tensor(x), paddle.to_tensor(off), paddle.to_tensor(w),
            paddle.to_tensor(b), padding=1,
            mask=None if m is None else paddle.to_tensor(m)).numpy()
        ref = _naive_deform_conv2d(x, off, w, b, padding=1, mask=m)
        np.testing.assert_allclose(ours, ref, atol=1e-4)

    def test_zero_offset_equals_conv(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(1)
        x = rng.randn(2, 3, 8, 8).astype("float32")
        w = rng.randn(4, 3, 3, 3).astype("float32") * 0.2
        off = np.zeros((2, 18, 8, 8), np.float32)
        ours = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                               paddle.to_tensor(w), padding=1).numpy()
        conv = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        padding=1).numpy()
        np.testing.assert_allclose(ours, conv, atol=1e-4)

    def test_layer_and_grad(self):
        layer = V.DeformConv2D(3, 4, 3, padding=1)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(1, 3, 6, 6).astype("float32"))
        x.stop_gradient = False
        off = paddle.to_tensor(
            np.random.RandomState(3).randn(1, 18, 6, 6).astype("float32")
            * 0.1)
        off.stop_gradient = False
        out = layer(x, off)
        assert out.shape == [1, 4, 6, 6]
        out.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert np.isfinite(off.grad.numpy()).all()
        assert layer.weight.grad is not None


class TestYolo:
    def _head(self, rng, N=2, S=3, cls=4, H=5):
        return rng.randn(N, S * (5 + cls), H, H).astype("float32") * 0.5

    def test_yolo_box_shapes_and_range(self):
        rng = np.random.RandomState(0)
        x = self._head(rng)
        img = np.array([[320, 480], [320, 480]], np.int32)
        boxes, scores = V.yolo_box(paddle.to_tensor(x),
                                   paddle.to_tensor(img),
                                   anchors=[10, 13, 16, 30, 33, 23],
                                   class_num=4, conf_thresh=0.0,
                                   downsample_ratio=32)
        b, s = boxes.numpy(), scores.numpy()
        assert b.shape == (2, 3 * 5 * 5, 4) and s.shape == (2, 75, 4)
        assert (b[..., 0] >= 0).all() and (b[..., 2] <= 479).all()
        assert (b[..., 1] >= 0).all() and (b[..., 3] <= 319).all()
        assert (s >= 0).all() and (s <= 1).all()

    def test_yolo_box_conf_thresh_zeroes(self):
        rng = np.random.RandomState(1)
        x = self._head(rng)
        img = np.full((2, 2), 320, np.int32)
        _, s_all = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                              [10, 13, 16, 30, 33, 23], 4, 0.0, 32)
        b_hi, s_hi = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                                [10, 13, 16, 30, 33, 23], 4, 0.999, 32)
        assert np.abs(s_hi.numpy()).sum() < np.abs(s_all.numpy()).sum()
        assert (np.abs(b_hi.numpy()).sum(-1) > 0).mean() < 0.05

    def test_yolo_loss_finite_and_positive(self):
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(self._head(rng, N=2))
        gt = np.zeros((2, 3, 4), np.float32)
        gt[:, 0] = [0.5, 0.5, 0.3, 0.4]      # one real box; rest padding
        lbl = np.zeros((2, 3), np.int64)
        loss = V.yolo_loss(x, paddle.to_tensor(gt), paddle.to_tensor(lbl),
                           anchors=[10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
                                    59, 119, 116, 90, 156, 198, 373, 326],
                           anchor_mask=[6, 7, 8], class_num=4,
                           ignore_thresh=0.7, downsample_ratio=32)
        lv = loss.numpy()
        assert lv.shape == (2,) and np.isfinite(lv).all() and (lv > 0).all()

    def test_yolo_loss_grad_and_descent(self):
        rng = np.random.RandomState(3)
        xv = self._head(rng, N=1)
        gt = np.zeros((1, 2, 4), np.float32)
        gt[:, 0] = [0.5, 0.5, 0.5, 0.5]
        lbl = np.zeros((1, 2), np.int64)
        kw = dict(anchors=[116, 90, 156, 198, 373, 326],
                  anchor_mask=[0, 1, 2], class_num=4,
                  ignore_thresh=0.7, downsample_ratio=32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        loss = V.yolo_loss(x, paddle.to_tensor(gt), paddle.to_tensor(lbl),
                           **kw)
        loss.sum().backward()
        g = x.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0
        # one SGD step reduces the loss
        x2 = paddle.to_tensor(xv - 0.5 * g)
        l2 = V.yolo_loss(x2, paddle.to_tensor(gt), paddle.to_tensor(lbl),
                         **kw)
        assert float(l2.sum()) < float(loss.sum())

    def test_yolo_loss_no_gt_only_objectness(self):
        rng = np.random.RandomState(4)
        x = paddle.to_tensor(self._head(rng, N=1))
        gt = np.zeros((1, 2, 4), np.float32)    # all padding
        lbl = np.zeros((1, 2), np.int64)
        loss = V.yolo_loss(x, paddle.to_tensor(gt), paddle.to_tensor(lbl),
                           anchors=[116, 90, 156, 198, 373, 326],
                           anchor_mask=[0, 1, 2], class_num=4,
                           ignore_thresh=0.7, downsample_ratio=32)
        assert float(loss.sum()) > 0   # negatives still pay objectness


class TestImageIO:
    def test_read_file_decode_jpeg(self):
        from PIL import Image
        arr = (np.random.RandomState(5).rand(16, 20, 3) * 255).astype("uint8")
        path = os.path.join(tempfile.mkdtemp(), "img.jpg")
        Image.fromarray(arr).save(path, quality=95)
        raw = V.read_file(path)
        assert raw.dtype == np.uint8 and raw.shape[0] > 100
        img = V.decode_jpeg(raw, mode="rgb")
        assert img.shape == [3, 16, 20]
        gray = V.decode_jpeg(raw, mode="gray")
        assert gray.shape == [1, 16, 20]


class TestDistributionLongtail:
    def test_mvn_diag(self):
        import paddle_tpu.distribution as D
        loc = np.array([0.0, 1.0], np.float32)
        scale = np.array([1.0, 2.0], np.float32)
        d = D.MultivariateNormalDiag(loc, scale)
        s = d.sample((1000,)).numpy()
        assert s.shape == (1000, 2)
        np.testing.assert_allclose(s.mean(0), loc, atol=0.25)
        # log_prob vs scipy closed form (independent normals)
        from scipy import stats
        v = np.array([[0.5, 0.5]], np.float32)
        ref = (stats.norm.logpdf(0.5, 0, 1)
               + stats.norm.logpdf(0.5, 1, 2))
        np.testing.assert_allclose(d.log_prob(v).numpy()[0], ref, atol=1e-5)
        # KL(p, p) == 0
        assert abs(float(d.kl_divergence(d).numpy())) < 1e-6
        ent_ref = (stats.norm.entropy(0, 1) + stats.norm.entropy(1, 2))
        np.testing.assert_allclose(float(d.entropy().numpy()), ent_ref,
                                   atol=1e-5)

    def test_sampling_id(self):
        import paddle_tpu.distribution as D
        p = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]], np.float32)
        idx = D.sampling_id(paddle.to_tensor(p)).numpy()
        np.testing.assert_array_equal(idx, [1, 0])

    def test_require_version(self):
        paddle.utils.require_version("0.0.1")
        with pytest.raises(Exception):
            paddle.utils.require_version("99.0")
