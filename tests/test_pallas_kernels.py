"""Pallas kernels vs reference math (interpret mode on the CPU mesh).

Models the reference's fused-op unittests (ref: python/paddle/fluid/tests/
unittests/test_fused_attention_op.py, test_fused_feedforward_op.py,
test_layer_norm_op.py): fused kernel output must match the unfused
composition, and gradients must flow."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import norms, fused_ffn as ffn_mod
from paddle_tpu.ops.pallas.flash_attn import flash_attention, _ref_attention

# model-level heavyweight suite: full train steps on the CPU mesh —
# runs in the slow tier, outside the tier-1 wall-clock budget
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("shape", [(8, 128), (2, 16, 256), (64, 384)])
def test_layer_norm_matches_ref(shape):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    g = jnp.asarray(rng.randn(shape[-1]), jnp.float32)
    b = jnp.asarray(rng.randn(shape[-1]), jnp.float32)
    got = norms.layer_norm(x, g, b, 1e-5, True)       # pallas interpret
    want = norms._ref_layer_norm(x, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("shape", [(8, 128), (4, 8, 256)])
def test_rms_norm_matches_ref(shape):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    g = jnp.asarray(rng.randn(shape[-1]), jnp.float32)
    got = norms.rms_norm(x, g, 1e-6, True)
    want = norms._ref_rms_norm(x, g, 1e-6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_layer_norm_grads():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 128), jnp.float32)
    g = jnp.asarray(rng.randn(128), jnp.float32)
    b = jnp.asarray(rng.randn(128), jnp.float32)

    def f_pallas(x, g, b):
        return jnp.sum(jnp.sin(norms.layer_norm(x, g, b, 1e-5, True)))

    def f_ref(x, g, b):
        return jnp.sum(jnp.sin(norms._ref_layer_norm(x, g, b, 1e-5)))

    gp = jax.grad(f_pallas, (0, 1, 2))(x, g, b)
    gr = jax.grad(f_ref, (0, 1, 2))(x, g, b)
    for a, w in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w), atol=1e-4)


@pytest.mark.parametrize("M,H,F", [(128, 128, 256), (256, 256, 512)])
def test_fused_ffn_matches_ref(M, H, F):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(M, H) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.randn(H, F) * 0.05, jnp.float32)
    b1 = jnp.asarray(rng.randn(F) * 0.01, jnp.float32)
    w2 = jnp.asarray(rng.randn(F, H) * 0.05, jnp.float32)
    b2 = jnp.asarray(rng.randn(H) * 0.01, jnp.float32)
    got = ffn_mod.fused_ffn(x, w1, b1, w2, b2, True)
    want = ffn_mod._ref_ffn(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=1e-4)


def test_fused_ffn_batched_and_grads():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 64, 128) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.randn(128, 256) * 0.05, jnp.float32)
    b1 = jnp.zeros((256,), jnp.float32)
    w2 = jnp.asarray(rng.randn(256, 128) * 0.05, jnp.float32)
    b2 = jnp.zeros((128,), jnp.float32)
    got = ffn_mod.fused_ffn(x, w1, b1, w2, b2, True)
    assert got.shape == x.shape

    def f(x, w1, w2):
        return jnp.sum(ffn_mod.fused_ffn(x, w1, b1, w2, b2, True) ** 2)

    def fr(x, w1, w2):
        return jnp.sum(ffn_mod._ref_ffn(x, w1, b1, w2, b2) ** 2)

    gp = jax.grad(f, (0, 1, 2))(x, w1, w2)
    gr = jax.grad(fr, (0, 1, 2))(x, w1, w2)
    for a, w in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                   atol=2e-3, rtol=1e-3)


def test_incubate_fused_ops_eager():
    """incubate.fused_feedforward / fused_layer_norm run on the eager tape
    and backprop into their weights."""
    import paddle_tpu as paddle

    rng = np.random.RandomState(6)
    x = paddle.to_tensor(rng.randn(16, 128).astype(np.float32))
    w1 = paddle.to_tensor((rng.randn(128, 256) * 0.05).astype(np.float32),
                          stop_gradient=False)
    b1 = paddle.to_tensor(np.zeros(256, np.float32), stop_gradient=False)
    w2 = paddle.to_tensor((rng.randn(256, 128) * 0.05).astype(np.float32),
                          stop_gradient=False)
    b2 = paddle.to_tensor(np.zeros(128, np.float32), stop_gradient=False)
    out = paddle.incubate.fused_feedforward(x, w1, b1, w2, b2)
    assert tuple(out.shape) == (16, 128)
    out.sum().backward()
    assert w1.grad is not None and np.abs(np.asarray(
        w1.grad.numpy())).sum() > 0

    g = paddle.to_tensor(np.ones(128, np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.zeros(128, np.float32), stop_gradient=False)
    y = paddle.incubate.fused_layer_norm(x, g, b)
    y.sum().backward()
    assert g.grad is not None
    np.testing.assert_allclose(
        np.asarray(y.numpy()),
        np.asarray(norms._ref_layer_norm(
            jnp.asarray(np.asarray(x.numpy())), jnp.ones(128),
            jnp.zeros(128), 1e-5)),
        atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_fallback_matches_dense(causal):
    """On CPU flash_attention routes to the fused XLA path; check the
    custom_vjp wiring end to end anyway."""
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 128, 4, 64) * 0.1, jnp.float32)
    k = jnp.asarray(rng.randn(2, 128, 4, 64) * 0.1, jnp.float32)
    v = jnp.asarray(rng.randn(2, 128, 4, 64) * 0.1, jnp.float32)
    out = flash_attention(q, k, v, causal)
    want = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal)))(q)
    gw = jax.grad(lambda q: jnp.sum(_ref_attention(q, k, v, causal)))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gw), atol=1e-5)


@pytest.mark.parametrize("B,N,H,D", [(2, 256, 4, 64), (1, 512, 2, 128)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_kernel_interpret(B, N, H, D, causal):
    """The actual TPU kernel body (online-softmax tiling, causal block skip)
    vs unfused reference, via pallas interpret mode on CPU."""
    from paddle_tpu.ops.pallas.flash_attn import _flash_attention_tpu

    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    got = _flash_attention_tpu(q, k, v, causal, interpret=True)
    want = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_flash_attention_kernel_interpret_uneven_blocks():
    """Sequence not a multiple of the k-block: masked tail must not leak."""
    from paddle_tpu.ops.pallas.flash_attn import _flash_attention_tpu

    rng = np.random.RandomState(8)
    q, k, v = [jnp.asarray(rng.randn(1, 384, 2, 64), jnp.float32)
               for _ in range(3)]
    got = _flash_attention_tpu(q, k, v, True, block_q=256, block_k=256,
                               interpret=True)
    want = _ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_flash_attention_kernel_cross_length_causal():
    """Nk != N (prefix-cache decode shape): causal mask must be bottom-right
    aligned like _ref_attention's tril(k=m-n)."""
    from paddle_tpu.ops.pallas.flash_attn import _flash_attention_tpu

    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 128, 2, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 320, 2, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 320, 2, 64), jnp.float32)
    got = _flash_attention_tpu(q, k, v, True, block_q=128, block_k=128,
                               interpret=True)
    want = _ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("B,N,Nk,H,D,causal", [
    (2, 256, 256, 2, 64, False),
    (2, 256, 256, 2, 64, True),
    (1, 384, 384, 2, 64, True),      # uneven tail blocks
    (1, 128, 320, 2, 64, True),      # cross-length (prefix-cache)
    (1, 512, 512, 1, 128, False),
])
def test_flash_attention_backward_kernel_interpret(B, N, Nk, H, D, causal):
    """Pallas backward (dq/dk/dv via saved-logsumexp recompute) vs XLA
    autodiff of the dense reference."""
    from paddle_tpu.ops.pallas.flash_attn import (_flash_attention_bwd_tpu,
                                                  _flash_attention_tpu)

    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, Nk, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, Nk, H, D), jnp.float32)
    do = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    out, lse = _flash_attention_tpu(q, k, v, causal, interpret=True,
                                    return_lse=True)
    dq, dk, dv = _flash_attention_bwd_tpu(q, k, v, out, lse, do, causal,
                                          interpret=True)
    _, vjp = jax.vjp(lambda a, b, c: _ref_attention(a, b, c, causal),
                     q, k, v)
    for got, want in zip((dq, dk, dv), vjp(do)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4)


@pytest.mark.parametrize("B,N,Nk,H,D,causal", [
    (2, 256, 256, 2, 64, False),
    (2, 256, 256, 2, 64, True),
    (1, 384, 384, 2, 64, True),      # uneven tail blocks
    (1, 128, 320, 2, 64, True),      # cross-length (prefix-cache)
    (1, 512, 512, 1, 128, False),
])
def test_flash_attention_fused_backward_interpret(B, N, Nk, H, D, causal):
    """FUSED backward (one kernel: dk/dv scratch + per-K-block dq
    partials) must match both the split kernels and the dense reference."""
    from paddle_tpu.ops.pallas.flash_attn import (_flash_attention_bwd_tpu,
                                                  _flash_attention_tpu)

    rng = np.random.RandomState(12)
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, Nk, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, Nk, H, D), jnp.float32)
    do = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    out, lse = _flash_attention_tpu(q, k, v, causal, interpret=True,
                                    return_lse=True)
    fused = _flash_attention_bwd_tpu(q, k, v, out, lse, do, causal,
                                     interpret=True, fused=True)
    split = _flash_attention_bwd_tpu(q, k, v, out, lse, do, causal,
                                     interpret=True, fused=False)
    _, vjp = jax.vjp(lambda a, b, c: _ref_attention(a, b, c, causal),
                     q, k, v)
    ref = vjp(do)
    for got, via_split, want in zip(fused, split, ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(via_split),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4)


def test_set_default_blocks_bwd_fused_flag():
    from paddle_tpu.ops.pallas import flash_attn as fa
    try:
        fa.set_default_blocks(bwd_fused=True)
        assert fa._BWD_FUSED is True
    finally:
        fa.set_default_blocks(bwd_fused=False)


def test_fused_ffn_block_override():
    """set_default_blocks installs a sweep-chosen tiling; shapes it does
    not divide fall back to the automatic choice (the kernel has no tail
    masking, so an invalid override must never reach pallas_call)."""
    from paddle_tpu.ops.pallas import fused_ffn as ff

    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(256, 256) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.randn(256, 512) * 0.05, jnp.float32)
    b1 = jnp.asarray(rng.randn(512) * 0.01, jnp.float32)
    w2 = jnp.asarray(rng.randn(512, 256) * 0.05, jnp.float32)
    b2 = jnp.asarray(rng.randn(256) * 0.01, jnp.float32)
    want = np.asarray(ff._ref_ffn(x, w1, b1, w2, b2))
    seen = []
    real_tpu = ff._fused_ffn_tpu

    def spy(x2d, w1, b1, w2, b2, block_m, block_f, interpret):
        seen.append((block_m, block_f))
        return real_tpu(x2d, w1, b1, w2, b2, block_m, block_f, interpret)

    try:
        ff._fused_ffn_tpu = spy
        ff.set_default_blocks((128, 256))        # divides exactly
        got = np.asarray(ff.fused_ffn(x, w1, b1, w2, b2, interpret=True))
        np.testing.assert_allclose(got, want, atol=2e-3)
        assert seen[-1] == (128, 256)
        ff.set_default_blocks((96, 640))         # divides nothing
        got2 = np.asarray(ff.fused_ffn(x, w1, b1, w2, b2, interpret=True))
        np.testing.assert_allclose(got2, want, atol=2e-3)
        # the invalid override must have fallen back to the automatic
        # choice, never reaching pallas_call (the kernel has no masking)
        auto = ff._pick_blocks(256, 256, 512, 4)
        assert seen[-1] == auto and auto != (96, 640)
    finally:
        ff._fused_ffn_tpu = real_tpu
        ff.set_default_blocks(None)
