"""Checkpoint/resume + nan-inf failure detection (SURVEY.md §2.11).

Models the reference's auto-checkpoint and nan-inf-utils tests (ref:
python/paddle/fluid/tests/unittests/test_auto_checkpoint.py,
test_nan_inf.py): full training-state round trip with exact RNG stream
restore, retention, atomicity; guard raises at the first non-finite op with
the op name, and the jit-side check passes finite trees through.
"""
import os
import time
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import CheckpointManager


def _step(net, opt, x, y):
    loss = paddle.nn.functional.mse_loss(net(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


def test_checkpoint_resume_bitwise():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(16, 2).astype(np.float32))

    def make():
        paddle.seed(7)
        net = paddle.nn.Linear(4, 2)
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                              step_size=3, gamma=0.5)
        opt = paddle.optimizer.Adam(learning_rate=sched,
                                    parameters=net.parameters())
        return net, opt, sched

    with tempfile.TemporaryDirectory() as d:
        # run A: 5 steps, checkpoint at 3, continue to 5
        net, opt, sched = make()
        mgr = CheckpointManager(d, keep=5)
        for i in range(1, 6):
            _step(net, opt, x, y)
            sched.step()
            mgr.save(i, model=net, optimizer=opt, scheduler=sched)
        wA = np.asarray(net.weight.numpy()).copy()
        rA = paddle.rand([3])   # post-training rng draw

        # run B: fresh objects, restore step 3, replay 4..5
        net2, opt2, sched2 = make()
        mgr2 = CheckpointManager(d, keep=5)
        step = mgr2.restore(model=net2, optimizer=opt2, scheduler=sched2,
                            step=3)
        assert step == 3
        for i in range(4, 6):
            _step(net2, opt2, x, y)
            sched2.step()
        np.testing.assert_array_equal(wA, np.asarray(net2.weight.numpy()))
        rB = paddle.rand([3])
        np.testing.assert_array_equal(np.asarray(rA.numpy()),
                                      np.asarray(rB.numpy()))


def test_checkpoint_retention_and_latest():
    net = paddle.nn.Linear(2, 2)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, keep=2)
        for i in (1, 2, 3, 4):
            mgr.save(i, model=net)
        kept = sorted(os.listdir(d))
        assert kept == ["step_3", "step_4"]
        assert mgr.latest_step() == 4
        assert mgr.restore(model=net) == 4


def test_checkpoint_restore_empty_dir():
    with tempfile.TemporaryDirectory() as d:
        assert CheckpointManager(d).restore(model=paddle.nn.Linear(2, 2)) \
            is None


def test_nan_guard_raises_with_op_name():
    from paddle_tpu.debug import NanInfError, check_nan_inf_guard

    x = paddle.to_tensor(np.asarray([1.0, 0.0], np.float32))
    with check_nan_inf_guard():
        paddle.add(x, x)                      # finite: fine
        with pytest.raises(NanInfError, match="log"):
            paddle.log(paddle.to_tensor(np.asarray([-1.0], np.float32)))
    # guard is scoped: outside it non-finite passes silently
    out = paddle.log(paddle.to_tensor(np.asarray([-1.0], np.float32)))
    assert np.isnan(np.asarray(out.numpy())).all()


def test_nan_guard_covers_taped_path():
    from paddle_tpu.debug import NanInfError, check_nan_inf_guard

    w = paddle.to_tensor(np.asarray([[1.0]], np.float32),
                         stop_gradient=False)
    with check_nan_inf_guard():
        with pytest.raises(NanInfError):
            paddle.matmul(w, paddle.to_tensor(
                np.asarray([[np.inf]], np.float32)))


def test_check_numerics_inside_jit():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.debug import check_numerics, finite_mask

    @jax.jit
    def f(x):
        return check_numerics({"a": x * 2}, "train_step")["a"]

    np.testing.assert_allclose(np.asarray(f(jnp.ones(3))), 2 * np.ones(3))
    assert bool(finite_mask({"g": jnp.ones(2), "h": jnp.zeros(())}))
    assert not bool(finite_mask({"g": jnp.asarray([np.inf])}))


def test_nan_guard_skips_traced_ops():
    """Guard must not explode on tracers when a jitted/to_static function
    is compiled while the eager guard is enabled."""
    from paddle_tpu.debug import check_nan_inf_guard

    net = paddle.nn.Linear(3, 3)
    snet = paddle.jit.to_static(net)
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    with check_nan_inf_guard():
        out = snet(x)
    assert tuple(out.shape) == (2, 3)


def test_checkpoint_order_survives_mtime_loss():
    """Retention/latest must follow the explicit save-sequence number, not
    filesystem mtime (cp/git/object-store transports rewrite mtimes): an
    operator who rewinds to an earlier step and trains on must have the
    NEW low-numbered checkpoints treated as the live run."""
    net = paddle.nn.Linear(2, 2)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, keep=2)
        mgr.save(10, model=net)
        mgr.save(20, model=net)
        # rewind: step 5 saved AFTER step 20 is the live run
        mgr.save(5, model=net)
        assert mgr.latest_step() == 5
        kept = sorted(os.listdir(d))
        assert "step_5" in kept and "step_10" not in kept
        # scramble mtimes the way a cp -r without -p would
        now = time.time()
        for name in os.listdir(d):
            os.utime(os.path.join(d, name), (now, now))
        mgr2 = CheckpointManager(d, keep=2)
        assert mgr2.latest_step() == 5
        assert mgr2.restore(model=net) == 5


def test_checkpoint_seq_falls_back_to_step_number():
    """Dirs from before the sequence file existed order by step number
    and sort OLDER than any seq-stamped dir."""
    net = paddle.nn.Linear(2, 2)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, keep=10)
        mgr.save(3, model=net)
        mgr.save(7, model=net)
        for s in (3, 7):   # simulate legacy checkpoints: no seq file
            os.remove(os.path.join(d, f"step_{s}", "save_seq"))
        assert mgr.latest_step() == 7
        mgr.save(1, model=net)          # new-format save wins
        assert mgr.latest_step() == 1
