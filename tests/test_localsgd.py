"""LocalSGD: periodic param averaging over the dp axis (VERDICT r4 item 7;
ref fleet/meta_optimizers/localsgd_optimizer.py)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu.parallel.localsgd import (localsgd_param_sync,
                                          LocalSGDOptimizer)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("dp",))


class TestSPMDParamSync:
    def test_ranks_diverge_then_equalize_on_boundary(self):
        """Per-rank params drift for k-1 local steps, snap to the global
        mean exactly on each k-step boundary — the whole loop jitted."""
        mesh = _mesh()
        k = 3

        # per-rank param copy [dp, 2]; per-rank grads differ by rank
        w0 = jnp.zeros((8, 2), jnp.float32)

        @jax.jit
        def run_step(w, step):
            def body(w):
                rank = jax.lax.axis_index("dp").astype(jnp.float32)
                g = jnp.stack([rank + 1.0, -(rank + 1.0)])  # rank-specific
                w = w - 0.1 * g[None, :]                    # local SGD
                w = localsgd_param_sync(w, step, k_steps=k, begin_step=k)
                return w
            return shard_map(body, mesh=mesh, in_specs=P("dp"),
                             out_specs=P("dp"))(w)

        w = w0
        for step in range(1, 8):
            w = run_step(w, jnp.int32(step))
            host = np.asarray(w)
            spread = np.abs(host - host.mean(0, keepdims=True)).max()
            if step % k == 0:
                assert spread < 1e-6, f"step {step}: not averaged"
            else:
                assert spread > 1e-3, f"step {step}: averaged too early"

    def test_average_value_is_global_mean(self):
        mesh = _mesh()
        w = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

        def body(w):
            return localsgd_param_sync(w, jnp.int32(4), k_steps=2,
                                       begin_step=2)
        out = shard_map(body, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(w)
        np.testing.assert_allclose(np.asarray(out), 3.5)


class TestFleetWrapper:
    def test_wrapper_steps_and_converges(self):
        import paddle_tpu as paddle

        w = paddle.to_tensor(np.array([4.0], "float32"),
                             stop_gradient=False)
        inner = paddle.optimizer.SGD(learning_rate=0.3, parameters=[w])
        opt = LocalSGDOptimizer(inner, k_steps=2)
        for _ in range(20):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert abs(float(w.numpy())) < 1e-2

    def test_static_minimize_warns_not_silent(self):
        import warnings
        import paddle_tpu as paddle
        from paddle_tpu import fluid

        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("ls_x", [2], dtype="float32")
                loss = fluid.layers.reduce_mean(fluid.layers.fc(x, 1))
                inner = paddle.optimizer.SGD(learning_rate=0.1)
                opt = LocalSGDOptimizer(inner, k_steps=2)
                with warnings.catch_warnings(record=True) as rec:
                    warnings.simplefilter("always")
                    opt.minimize(loss)
                assert any("localsgd_param_sync" in str(r.message)
                           for r in rec)
        finally:
            paddle.disable_static()

    def test_fleet_strategy_wires_localsgd_and_warns_na_flags(self):
        import warnings
        import paddle_tpu as paddle
        from paddle_tpu.distributed import fleet

        strat = fleet.DistributedStrategy()
        strat.localsgd = True
        strat.localsgd_configs = {"k_steps": 4, "begin_step": 2}
        strat.dgc = True
        strat.fp16_allreduce = True

        w = paddle.to_tensor(np.ones(2, "float32"), stop_gradient=False)
        inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        f = fleet.fleet
        f._strategy = strat       # bypass init (no mesh needed here)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            opt = f.distributed_optimizer(inner)
        msgs = "".join(str(r.message) for r in rec)
        assert "dgc" in msgs and "fp16_allreduce" in msgs
        assert isinstance(opt, LocalSGDOptimizer)
        assert opt._k == 4 and opt._begin == 2


def test_a_sync_maps_to_localsgd_with_warning():
    """strategy.a_sync (the reference's geo-SGD PS mode) must map onto
    LocalSGD periodic averaging — loudly, never silently ignored."""
    import warnings
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.parallel.localsgd import LocalSGDOptimizer

    strat = fleet.DistributedStrategy()
    strat.a_sync = True
    strat.a_sync_configs = {"k_steps": 37}
    fleet.init(is_collective=True, strategy=strat)
    lin = paddle.nn.Linear(2, 2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=lin.parameters()))
    assert any("a_sync" in str(x.message) for x in w)
    assert isinstance(opt, LocalSGDOptimizer)
    assert opt._k == 37
