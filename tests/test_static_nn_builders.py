"""static.nn layer builders beyond the core set (ref fluid/layers/nn.py):
conv2d_transpose, conv3d, prelu, group_norm, instance_norm,
bilinear_tensor_product, spectral_norm — built into a Program and run
through the Executor."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static


def test_static_nn_builders_build_and_run():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            img = static.data("img", [2, 4, 8, 8], "float32")
            vol = static.data("vol", [2, 3, 4, 8, 8], "float32")
            x2 = static.data("x2", [2, 3], "float32")
            y2 = static.data("y2", [2, 5], "float32")
            a = static.nn.conv2d_transpose(img, 6, 3)
            b = static.nn.conv3d(vol, 5, 3)
            c = static.nn.prelu(img)
            d = static.nn.group_norm(img, groups=2)
            e = static.nn.instance_norm(img)
            f = static.nn.bilinear_tensor_product(x2, y2, 7)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"img": rng.randn(2, 4, 8, 8).astype(np.float32),
                "vol": rng.randn(2, 3, 4, 8, 8).astype(np.float32),
                "x2": rng.randn(2, 3).astype(np.float32),
                "y2": rng.randn(2, 5).astype(np.float32)}
        outs = exe.run(main, feed=feed, fetch_list=[a, b, c, d, e, f])
        shapes = [o.shape for o in outs]
        assert shapes == [(2, 6, 10, 10), (2, 5, 2, 6, 6), (2, 4, 8, 8),
                          (2, 4, 8, 8), (2, 4, 8, 8), (2, 7)], shapes
        for o in outs:
            assert np.isfinite(o).all()
        # group_norm output: per-group normalized => ~zero mean
        assert abs(outs[3].mean()) < 0.1
    finally:
        paddle.disable_static()


def test_static_nn_spectral_norm():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            w = static.data("w", [8, 6], "float32")
            out = static.nn.spectral_norm(w, power_iters=3)
        exe = static.Executor()
        rng = np.random.RandomState(1)
        wv = rng.randn(8, 6).astype(np.float32) * 5
        got, = exe.run(main, feed={"w": wv}, fetch_list=[out])
        assert np.isfinite(got).all()
        # largest singular value of the normalized weight is ~1
        s = np.linalg.svd(got, compute_uv=False)
        assert s[0] < 2.0, s
    finally:
        paddle.disable_static()
