"""BeamSearchDecoder + dynamic_decode + gather_tree end-to-end: beam
search must beat greedy on a rigged distribution, and the returned paths
must be ancestry-consistent (the gather_tree backtrace)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


V = 4          # vocab: 0=start-ish filler, 1=A, 2=B, 3=end
END = 3


class RiggedCell(nn.Layer):
    """Logits depend only on the input token:
      from token 0 (start): A has p=.55, B p=.45
      from A: near-uniform over {0, 1, 2} (p<=.35 each), end tiny
      from B: end has p=.9
    Greedy takes A then flounders (.55 * .35 = .19); the optimal path is
    B -> end (.45 * .9 = .405).  Beam >= 2 must find it."""

    def __init__(self):
        super().__init__()
        probs = np.full((V, V), 1e-3, np.float32)
        probs[0] = [1e-3, 0.55, 0.45 - 2e-3, 1e-3]
        probs[1] = [0.33, 0.33, 0.33, 0.01 - 1e-3 * 0]
        probs[1] = probs[1] / probs[1].sum()
        probs[2] = [0.04, 0.03, 0.03, 0.90]
        probs[END] = [1e-3, 1e-3, 1e-3, 1.0 - 3e-3]
        self._logits = np.log(probs)

    def forward(self, inp, states):
        # inp: [N] int tokens; states: [N, 1] dummy carry
        import jax.numpy as jnp
        from paddle_tpu.ops.dispatch import call
        table = self._logits

        def _f(tok, st):
            return jnp.asarray(table)[tok.astype(jnp.int32)], st
        return call(_f, inp, states, _name="rigged_cell")


def test_beam_search_finds_nongreedy_optimum():
    cell = RiggedCell()
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=END,
                               beam_size=3)
    h0 = paddle.to_tensor(np.zeros((2, 1), np.float32))
    out, states = nn.dynamic_decode(dec, inits=h0, max_step_num=4)
    tokens = out.numpy()              # [B, T, beam]
    # best beam (slot 0): B then end
    assert tokens.shape[0] == 2 and tokens.shape[2] == 3
    for b in range(2):
        assert tokens[b, 0, 0] == 2, tokens[b, :, 0]   # B first
        assert tokens[b, 1, 0] == END
    # final log prob of the best beam ~ log(.45*.9)
    _, log_probs, _ = states
    np.testing.assert_allclose(log_probs.numpy()[0, 0],
                               np.log(0.448 * 0.9), atol=0.05)


def test_beam_paths_are_ancestry_consistent():
    """Every returned beam must be a valid path: its step-t token's
    distribution must have been conditioned on its step t-1 token (the
    raw per-step outputs without gather_tree can interleave beams)."""
    cell = RiggedCell()
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=END,
                               beam_size=2)
    h0 = paddle.to_tensor(np.zeros((1, 1), np.float32))
    out, _ = nn.dynamic_decode(dec, inits=h0, max_step_num=3)
    tokens = out.numpy()[0]           # [T, beam]
    # path consistency for this rig: an END at step t>0 can only follow
    # B (0.9) or END itself — never A (p(end|A) ~ 0.003 is dominated)
    for k in range(tokens.shape[1]):
        for t in range(1, tokens.shape[0]):
            if tokens[t, k] == END and tokens[t - 1, k] == 1:
                raise AssertionError(
                    f"beam {k} has END after A — broken ancestry: "
                    f"{tokens[:, k]}")


def test_time_major_output():
    cell = RiggedCell()
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=END,
                               beam_size=2)
    h0 = paddle.to_tensor(np.zeros((3, 1), np.float32))
    out_tm, _ = nn.dynamic_decode(dec, inits=h0, max_step_num=3,
                                  output_time_major=True)
    out_bm, _ = nn.dynamic_decode(dec, inits=h0, max_step_num=3)
    assert out_tm.shape[1] == 3 and out_bm.shape[0] == 3
    np.testing.assert_array_equal(out_tm.numpy().transpose(1, 0, 2),
                                  out_bm.numpy())
