"""Autograd tape tests: backward vs jax.grad golden (SURVEY.md §4)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle


def test_simple_chain():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_matmul_grad():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 2).astype(np.float32)
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    loss = paddle.matmul(ta, tb).sum()
    loss.backward()
    ga, gb = jax.grad(lambda x, y: (x @ y).sum(), argnums=(0, 1))(a, b)
    np.testing.assert_allclose(ta.grad.numpy(), ga, rtol=1e-5)
    np.testing.assert_allclose(tb.grad.numpy(), gb, rtol=1e-5)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y1 = (x * 2).sum()
    y1.backward()
    y2 = (x * 3).sum()
    y2.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5, 5])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach_breaks_graph():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = (x * x).detach()
    z = y * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [9.0])


def test_diamond_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = x * 4
    (a * b).sum().backward()
    # d/dx (12 x^2) = 24 x = 48
    np.testing.assert_allclose(x.grad.numpy(), [48.0])


def test_non_scalar_backward_with_grad():
    x = paddle.to_tensor([[1.0, 2.0]], stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor([[1.0, 0.5]]))
    np.testing.assert_allclose(x.grad.numpy(), [[2.0, 1.0]])


def test_paddle_grad_api():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x ** 3).sum()
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [3, 12], rtol=1e-6)
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y._node is None
    z = x * 2
    assert z._node is not None


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_multi_output_grad():
    x = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32),
                         stop_gradient=False)
    parts = paddle.split(x, 2, axis=0)
    loss = (parts[0].sum() * 2 + parts[1].sum())
    loss.backward()
    expect = np.concatenate([np.full((2, 3), 2.0), np.full((2, 3), 1.0)])
    np.testing.assert_allclose(x.grad.numpy(), expect)


def test_softmax_ce_grad_matches_jax():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([0, 2, 1, 4])
    t = paddle.to_tensor(logits, stop_gradient=False)
    loss = paddle.nn.functional.cross_entropy(t, paddle.to_tensor(labels))
    loss.backward()

    def ref(lg):
        lp = jax.nn.log_softmax(lg, -1)
        return -lp[jnp.arange(4), labels].mean()
    g = jax.grad(ref)(logits)
    np.testing.assert_allclose(t.grad.numpy(), g, rtol=1e-4, atol=1e-6)


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_grad_wrt_intermediate():
    # regression: paddle.grad silently returned zeros for non-leaf inputs
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    z = y * 3
    (g,) = paddle.grad(z, y)
    np.testing.assert_allclose(g.numpy(), [3.0])
    (gx,) = paddle.grad(z, x)
    np.testing.assert_allclose(gx.numpy(), [6.0])


def test_selu_large_input_grad_finite():
    import paddle_tpu.nn.functional as F
    x = paddle.to_tensor([100.0, -100.0, 0.5], stop_gradient=False)
    F.selu(x).sum().backward()
    assert np.all(np.isfinite(x.grad.numpy()))


def test_double_backward_error_message():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    l = (x * x).sum()
    l.backward()
    with pytest.raises(RuntimeError, match="retain_graph"):
        l.backward()


def test_register_hook_observes_and_rewrites_grad():
    """ref: VarBase._register_grad_hook semantics — hook sees the incoming
    grad, a non-None return replaces it; handles are removable."""
    import paddle_tpu as paddle

    w = paddle.to_tensor(np.asarray([2.0, 3.0], np.float32),
                         stop_gradient=False)
    seen = []
    handle = w.register_hook(lambda g: seen.append(
        np.asarray(g.numpy()).copy()) or g * 10)
    (w * w).sum().backward()
    np.testing.assert_allclose(seen[0], [4.0, 6.0])
    np.testing.assert_allclose(np.asarray(w.grad.numpy()), [40.0, 60.0])

    handle.remove()
    w.clear_grad()
    (w * w).sum().backward()
    np.testing.assert_allclose(np.asarray(w.grad.numpy()), [4.0, 6.0])


def test_register_hook_on_intermediate_tensor():
    """Hooks on non-leaf tensors fire with the activation's complete grad
    and rewrites propagate to upstream leaves (VarBase semantics)."""
    import paddle_tpu as paddle

    w = paddle.to_tensor(np.asarray([3.0], np.float32), stop_gradient=False)
    y = w * w                     # intermediate
    seen = []
    y.register_hook(lambda g: seen.append(np.asarray(g.numpy()).copy())
                    or g * 2)
    (y * 5).sum().backward()
    np.testing.assert_allclose(seen[0], [5.0])          # d(5y)/dy
    # rewrite doubled y's grad -> w.grad = 2*5 * dy/dw = 10 * 2w = 60
    np.testing.assert_allclose(np.asarray(w.grad.numpy()), [60.0])


def test_register_hook_self_removal_does_not_skip_next():
    import paddle_tpu as paddle

    w = paddle.to_tensor(np.asarray([1.0], np.float32), stop_gradient=False)
    fired = []
    handle1 = w.register_hook(lambda g: (fired.append("h1"),
                                         handle1.remove())[0])
    w.register_hook(lambda g: fired.append("h2"))
    (w * 2).sum().backward()
    assert fired == ["h1", "h2"]


def test_register_hook_fires_once_under_paddle_grad():
    """Hook on a tensor that is BOTH a node output and a paddle.grad input
    must fire exactly once; the rewritten grad is what paddle.grad returns
    and what flows upstream."""
    import paddle_tpu as paddle

    w = paddle.to_tensor(np.asarray([3.0], np.float32), stop_gradient=False)
    y = w * w
    calls = []
    y.register_hook(lambda g: calls.append(np.asarray(g.numpy()).copy())
                    or g * 2)
    (gy,) = paddle.grad((y * 5).sum(), [y], retain_graph=False)
    assert len(calls) == 1, calls
    np.testing.assert_allclose(calls[0], [5.0])
    np.testing.assert_allclose(np.asarray(gy.numpy()), [10.0])
