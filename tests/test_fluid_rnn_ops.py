"""Numpy-golden tests for the fluid dynamic-RNN op family + beam search
(ref python/paddle/fluid/layers/rnn.py:2262 dynamic_lstm, :2616
dynamic_lstmp, :2835 dynamic_gru, :2998 gru_unit, :2439 lstm, :3154
beam_search, :3313 beam_search_decode).

Every golden is a hand-rolled per-timestep numpy recurrence following the
reference formulas — independent of the lax.scan implementation.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_lstm_step(x4, h, c, w, b, use_peepholes):
    """Reference lstm_op step: gate columns {c, i, f, o}."""
    D = h.shape[-1]
    g = x4 + h @ w + b[:, :4 * D]
    gc, gi, gf, go = np.split(g, 4, axis=-1)
    if use_peepholes:
        gi = gi + b[:, 4 * D:5 * D] * c
        gf = gf + b[:, 5 * D:6 * D] * c
    i, f = sigmoid(gi), sigmoid(gf)
    c_new = f * c + i * np.tanh(gc)
    go = go + (b[:, 6 * D:7 * D] * c_new if use_peepholes else 0.0)
    h_new = sigmoid(go) * np.tanh(c_new)
    return h_new, c_new


@pytest.mark.parametrize("use_peepholes", [False, True])
@pytest.mark.parametrize("is_reverse", [False, True])
def test_dynamic_lstm_golden(use_peepholes, is_reverse):
    rng = np.random.RandomState(0)
    B, T, D = 3, 5, 4
    x = rng.randn(B, T, 4 * D).astype(np.float32) * 0.5
    lens = np.array([5, 3, 4], np.int32)
    w = rng.randn(D, 4 * D).astype(np.float32) * 0.3
    b = rng.randn(1, (7 if use_peepholes else 4) * D).astype(np.float32) * 0.1

    hid, cell = fluid.layers.dynamic_lstm(
        paddle.to_tensor(x), size=4 * D,
        param_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(w)),
        bias_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(b)),
        use_peepholes=use_peepholes, is_reverse=is_reverse,
        lengths=paddle.to_tensor(lens))

    # golden: per-row scalar recurrence over the VALID segment only
    want_h = np.zeros((B, T, D), np.float32)
    want_c = np.zeros((B, T, D), np.float32)
    for bi in range(B):
        L = lens[bi]
        seq = x[bi, :L][::-1] if is_reverse else x[bi, :L]
        h = np.zeros((1, D), np.float32)
        c = np.zeros((1, D), np.float32)
        outs = []
        for t in range(L):
            h, c = np_lstm_step(seq[t:t + 1], h, c, w, b, use_peepholes)
            outs.append((h[0], c[0]))
        if is_reverse:
            outs = outs[::-1]
        for t, (hh, cc) in enumerate(outs):
            want_h[bi, t] = hh
            want_c[bi, t] = cc

    np.testing.assert_allclose(hid.numpy(), want_h, atol=1e-5)
    np.testing.assert_allclose(cell.numpy(), want_c, atol=1e-5)


def test_dynamic_lstm_backward():
    rng = np.random.RandomState(1)
    B, T, D = 2, 4, 3
    x = paddle.to_tensor(rng.randn(B, T, 4 * D).astype(np.float32) * 0.5,
                         stop_gradient=False)
    hid, cell = fluid.layers.dynamic_lstm(x, size=4 * D, use_peepholes=True)
    loss = paddle.sum(hid * hid) + paddle.sum(cell)
    loss.backward()
    g = x.grad.numpy()
    assert g.shape == (B, T, 4 * D) and np.isfinite(g).all()
    assert np.abs(g).max() > 0


def test_dynamic_lstmp_golden():
    rng = np.random.RandomState(2)
    B, T, D, P = 2, 4, 4, 3
    x = rng.randn(B, T, 4 * D).astype(np.float32) * 0.5
    w = rng.randn(P, 4 * D).astype(np.float32) * 0.3
    wp = rng.randn(D, P).astype(np.float32) * 0.3
    b = rng.randn(1, 4 * D).astype(np.float32) * 0.1

    class SeqAssign:
        """Assign w then wp (dynamic_lstmp creates two params off one
        param_attr, reference-style)."""
        def __init__(self):
            self.vals = [w, wp]

        def __call__(self, shape, dtype):
            v = self.vals.pop(0)
            assert list(shape) == list(v.shape)
            return np.asarray(v, dtype)

    proj, cell = fluid.layers.dynamic_lstmp(
        paddle.to_tensor(x), size=4 * D, proj_size=P,
        param_attr=paddle.ParamAttr(initializer=SeqAssign()),
        bias_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(b)),
        use_peepholes=False, cell_clip=2.0, proj_clip=0.8)

    want_r = np.zeros((B, T, P), np.float32)
    for bi in range(B):
        r = np.zeros((1, P), np.float32)
        c = np.zeros((1, D), np.float32)
        for t in range(T):
            g = x[bi, t:t + 1] + r @ w + b
            gc, gi, gf, go = np.split(g, 4, axis=-1)
            c = sigmoid(gf) * c + sigmoid(gi) * np.tanh(gc)
            c = np.clip(c, -2.0, 2.0)
            h = sigmoid(go) * np.tanh(c)
            r = np.clip(np.tanh(h @ wp), -0.8, 0.8)
            want_r[bi, t] = r[0]

    np.testing.assert_allclose(proj.numpy(), want_r, atol=1e-5)
    assert cell.shape == [B, T, D]


@pytest.mark.parametrize("origin_mode", [False, True])
def test_dynamic_gru_golden(origin_mode):
    rng = np.random.RandomState(3)
    B, T, D = 3, 6, 4
    x = rng.randn(B, T, 3 * D).astype(np.float32) * 0.5
    lens = np.array([6, 2, 4], np.int32)
    w = rng.randn(D, 3 * D).astype(np.float32) * 0.3
    b = rng.randn(1, 3 * D).astype(np.float32) * 0.1

    out = fluid.layers.dynamic_gru(
        paddle.to_tensor(x), size=D,
        param_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(w)),
        bias_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(b)),
        origin_mode=origin_mode, lengths=paddle.to_tensor(lens))

    want = np.zeros((B, T, D), np.float32)
    for bi in range(B):
        h = np.zeros((1, D), np.float32)
        for t in range(lens[bi]):
            g = x[bi, t:t + 1] + b
            xu, xr, xc = np.split(g, 3, axis=-1)
            hg = h @ w[:, :2 * D]
            u = sigmoid(xu + hg[:, :D])
            r = sigmoid(xr + hg[:, D:])
            cand = np.tanh(xc + (r * h) @ w[:, 2 * D:])
            h = u * h + (1 - u) * cand if origin_mode \
                else (1 - u) * h + u * cand
            want[bi, t] = h[0]

    np.testing.assert_allclose(out.numpy(), want, atol=1e-5)


def test_gru_unit_golden():
    rng = np.random.RandomState(4)
    B, D = 4, 5
    x = rng.randn(B, 3 * D).astype(np.float32) * 0.5
    h0 = rng.randn(B, D).astype(np.float32) * 0.5
    w = rng.randn(D, 3 * D).astype(np.float32) * 0.3
    b = rng.randn(1, 3 * D).astype(np.float32) * 0.1

    h_new, rhp, gate = fluid.layers.gru_unit(
        paddle.to_tensor(x), paddle.to_tensor(h0), size=3 * D,
        param_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(w)),
        bias_attr=paddle.ParamAttr(
            initializer=paddle.nn.initializer.Assign(b)))

    g = x + b
    xu, xr, xc = np.split(g, 3, axis=-1)
    hg = h0 @ w[:, :2 * D]
    u = sigmoid(xu + hg[:, :D])
    r = sigmoid(xr + hg[:, D:])
    want_rhp = r * h0
    cand = np.tanh(xc + want_rhp @ w[:, 2 * D:])
    want_h = (1 - u) * h0 + u * cand       # origin_mode=False default

    np.testing.assert_allclose(h_new.numpy(), want_h, atol=1e-5)
    np.testing.assert_allclose(rhp.numpy(), want_rhp, atol=1e-5)
    np.testing.assert_allclose(gate.numpy(),
                               np.concatenate([u, r, cand], -1), atol=1e-5)


def test_lstm_multilayer_shapes_and_state():
    rng = np.random.RandomState(5)
    B, T, Din, D, L = 2, 5, 6, 4, 2
    x = paddle.to_tensor(rng.randn(B, T, Din).astype(np.float32))
    init_h = paddle.zeros([2 * L, B, D])
    init_c = paddle.zeros([2 * L, B, D])
    out, last_h, last_c = fluid.layers.lstm(
        x, init_h, init_c, max_len=T, hidden_size=D, num_layers=L,
        is_bidirec=True, is_test=True)
    assert out.shape == [B, T, 2 * D]
    assert last_h.shape == [2 * L, B, D]
    assert last_c.shape == [2 * L, B, D]
    # forward-direction last_h of the top layer must equal the out row at
    # the final step's forward half
    np.testing.assert_allclose(out.numpy()[:, -1, :D],
                               last_h.numpy()[2], atol=1e-5)
    # masked run: states freeze at each row's length
    out2, last_h2, _ = fluid.layers.lstm(
        x, None, None, max_len=T, hidden_size=D, num_layers=1,
        is_bidirec=False, is_test=True,
        lengths=paddle.to_tensor(np.array([5, 3], np.int32)))
    np.testing.assert_allclose(out2.numpy()[1, 2], last_h2.numpy()[0, 1],
                               atol=1e-6)
    assert np.all(out2.numpy()[1, 3:] == 0)


def test_beam_search_step_golden():
    # B=2, K=2, W=3; hand-check top-k over candidates with one ended beam
    pre_ids = np.array([[1], [9], [4], [2]], np.int64)      # row1 ended
    pre_scores = np.array([[-1.0], [-0.5], [-2.0], [-0.1]], np.float32)
    ids = np.arange(100, 124).reshape(4, 6)[:, :3].astype(np.int64)
    scores = np.array([
        [-1.2, -3.0, -0.9],
        [-9.0, -8.0, -7.0],     # ignored: beam ended (pre_id==9==end_id)
        [-0.3, -4.0, -2.5],
        [-0.2, -5.0, -0.4],
    ], np.float32)

    sel_ids, sel_scores, parents = fluid.layers.beam_search(
        paddle.to_tensor(pre_ids), paddle.to_tensor(pre_scores),
        paddle.to_tensor(ids), paddle.to_tensor(scores),
        beam_size=2, end_id=9, return_parent_idx=True)

    si, ss, pp = sel_ids.numpy(), sel_scores.numpy(), parents.numpy()
    # batch 0: candidates are beam0's scores and ended beam1's single
    # (end_id, -0.5) — top2: (-0.5, end) then (-0.9, id 102)
    assert ss[0, 0] == pytest.approx(-0.5) and si[0, 0] == 9 and pp[0] == 1
    assert ss[1, 0] == pytest.approx(-0.9) and si[1, 0] == 102 and pp[1] == 0
    # batch 1: top2 of {-0.3,-4,-2.5,-0.2,-5,-0.4} = -0.2 (beam1 cand0 =
    # id 118) then -0.3 (beam0 cand0 = id 112)
    assert ss[2, 0] == pytest.approx(-0.2) and si[2, 0] == 118 and pp[2] == 3
    assert ss[3, 0] == pytest.approx(-0.3) and si[3, 0] == 112 and pp[3] == 2


def test_beam_search_decode_backtrace():
    # one batch, K=2, T=3; construct a known tree
    # step0: beams pick ids [5, 7]; parents [0, 0]
    # step1: ids [3, 9(end)]; parents [0, 1]  (beam1 follows old beam1)
    # step2: ids [4, 9]; parents [0, 1]
    ids = [np.array([[5], [7]], np.int64),
           np.array([[3], [9]], np.int64),
           np.array([[4], [9]], np.int64)]
    scores = [np.array([[-0.1], [-0.2]], np.float32),
              np.array([[-0.3], [-0.4]], np.float32),
              np.array([[-0.5], [-0.6]], np.float32)]
    parents = [np.array([0, 0], np.int32),
               np.array([0, 1], np.int32),
               np.array([0, 1], np.int32)]

    sent_ids, sent_scores = fluid.layers.beam_search_decode(
        [paddle.to_tensor(i) for i in ids],
        [paddle.to_tensor(s) for s in scores],
        beam_size=2, end_id=9,
        parents=[paddle.to_tensor(p) for p in parents])

    si = sent_ids.numpy()
    ss = sent_scores.numpy()
    assert si.shape == (1, 2, 3)
    np.testing.assert_array_equal(si[0, 0], [5, 3, 4])
    # beam1 path: step1 ended with 9; after-end fill stays end_id
    np.testing.assert_array_equal(si[0, 1], [7, 9, 9])
    np.testing.assert_allclose(ss[0, 0], [-0.1, -0.3, -0.5], atol=1e-6)


def test_dynamic_gru_static_graph_mode():
    """The op family must also record into a static Program."""
    paddle.enable_static()
    try:
        rng = np.random.RandomState(6)
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("xg", [2, 4, 9], "float32")
            out = fluid.layers.dynamic_gru(x, size=3)
            loss = paddle.mean(out)
            exe = paddle.static.Executor()
            exe.run(startup)
            xv = rng.randn(2, 4, 9).astype(np.float32)
            (lv,) = exe.run(main, feed={"xg": xv}, fetch_list=[loss])
        assert np.isfinite(lv).all()
    finally:
        paddle.disable_static()
