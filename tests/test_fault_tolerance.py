"""Fault-tolerant runtime: collective watchdogs, crash-consistent async
checkpointing with digests + quarantine, resumable DataLoader state, the
deterministic fault-injection registry, and the supervised multi-process
kill-and-recover e2e (SURVEY.md §2.11; TorchElastic/Orbax design notes
in ISSUE 3)."""
import json
import os
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.testing import faults
from paddle_tpu.utils import CheckpointManager
from paddle_tpu.utils.checkpoint import checkpoint_stats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ----------------------------------------------------------- registry ----

class TestFaultRegistry:
    def test_spec_parsing_and_one_shot(self):
        faults.install("kill:step=4,rank=1,code=7; kv_fail:nth=2")
        assert faults.active()
        # rank filter: we are rank 0 (no PADDLE_TRAINER_ID in tests)
        assert faults.take("kill", step=4) is None
        faults.clear()
        faults.install("kill:step=4,code=7")
        assert faults.take("kill", step=3) is None
        got = faults.take("kill", step=4)
        assert got is not None and got["code"] == "7"
        assert faults.take("kill", step=4) is None      # one-shot

    def test_nth_counts_only_matching_calls(self):
        faults.install("kv_fail:nth=3,op=key_value_set")
        for _ in range(5):
            assert faults.take("kv_fail", op="wait_at_barrier") is None
        assert faults.take("kv_fail", op="key_value_set") is None   # 1st
        assert faults.take("kv_fail", op="key_value_set") is None   # 2nd
        assert faults.take("kv_fail", op="key_value_set") is not None
        assert faults.take("kv_fail", op="key_value_set") is None

    def test_restart_filter_reads_env(self, monkeypatch):
        faults.install("kill:step=1,restart=1")
        assert faults.take("kill", step=1) is None      # restart 0 now
        faults.clear()
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "1")
        faults.install("kill:step=1,restart=1")
        assert faults.take("kill", step=1) is not None

    def test_step_scoped_fault_never_fires_on_stepless_sites(self):
        """A step= filter must not match call sites with no step notion
        (collective hooks pass step=None) — firing at the first
        occurrence would corrupt the chaos scenario."""
        faults.install("collective_drop:step=5,op=all_reduce")
        assert faults.take("collective_drop", op="all_reduce") is None
        assert faults.take("collective_drop", op="all_reduce",
                           step=4) is None
        assert faults.take("collective_drop", op="all_reduce",
                           step=5) is not None

    def test_fired_counter_reaches_profiler(self):
        before = profiler.faults_stats().get("faults_fired", 0)
        faults.install("kv_fail:nth=1")
        assert faults.take("kv_fail", op="x") is not None
        assert profiler.faults_stats()["faults_fired"] == before + 1


# ----------------------------------------------------- checkpointing ----

def _make_state(seed=7):
    paddle.seed(seed)
    net = paddle.nn.Linear(4, 3)
    opt = paddle.optimizer.Momentum(0.1, parameters=net.parameters())
    return net, opt


def _train(net, opt, steps=1, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()


class TestChecksummedCheckpoints:
    def test_digests_written_and_verified(self, tmp_path):
        net, opt = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, model=net, optimizer=opt)
        d = tmp_path / "step_1" / "digests.json"
        assert d.exists()
        digests = json.loads(d.read_text())
        assert set(digests) == {"save_seq", "model.pdparams",
                                "opt.pdopt", "meta.pdstate"}
        mgr.verify(str(tmp_path / "step_1"))      # clean: no raise

    def test_corrupt_latest_quarantined_falls_back(self, tmp_path):
        """Satellite: a truncated/corrupt latest step dir is quarantined
        (step_N.corrupt) with a warning and restore resumes from the
        previous valid checkpoint."""
        net, opt = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        _train(net, opt)
        mgr.save(1, model=net, optimizer=opt)
        w1 = np.asarray(net.weight.numpy()).copy()
        _train(net, opt)
        mgr.save(2, model=net, optimizer=opt)
        # torn write: truncate the latest params file
        victim = tmp_path / "step_2" / "model.pdparams"
        data = victim.read_bytes()
        victim.write_bytes(data[:len(data) // 2])

        quarantined_before = checkpoint_stats()["checkpoints_quarantined"]
        net2, opt2 = _make_state(seed=11)
        mgr2 = CheckpointManager(str(tmp_path))
        with pytest.warns(RuntimeWarning, match="quarantined"):
            step = mgr2.restore(model=net2, optimizer=opt2)
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(net2.weight.numpy()), w1)
        assert (tmp_path / "step_2.corrupt").is_dir()
        assert not (tmp_path / "step_2").exists()
        stats = profiler.fast_path_summary()["faults"]
        assert stats["checkpoints_quarantined"] == quarantined_before + 1
        assert stats["digest_failures"] >= 1

    def test_explicit_corrupt_step_falls_back(self, tmp_path):
        net, opt = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(3, model=net)
        mgr.save(5, model=net)
        (tmp_path / "step_5" / "meta.pdstate").write_bytes(b"garbage")
        with pytest.warns(RuntimeWarning):
            assert mgr.restore(model=net, step=5) == 3

    def test_explicit_corrupt_step_falls_back_OLDER_never_newer(
            self, tmp_path):
        """Rolling back to a corrupt step must fall back to a checkpoint
        published BEFORE it — silently restoring the newer state the
        operator was rolling back from would be a correctness trap."""
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path), keep=10)
        mgr.save(3, model=net)
        mgr.save(5, model=net)
        mgr.save(9, model=net)            # the state being rolled back
        (tmp_path / "step_5" / "meta.pdstate").write_bytes(b"garbage")
        with pytest.warns(RuntimeWarning):
            assert mgr.restore(model=net, step=5) == 3    # not 9

    def test_all_corrupt_returns_none(self, tmp_path):
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, model=net)
        (tmp_path / "step_1" / "model.pdparams").write_bytes(b"x")
        with pytest.warns(RuntimeWarning):
            assert mgr.restore(model=net) is None

    def test_async_save_parity_and_publish_order(self, tmp_path):
        net, opt = _make_state()
        mgr = CheckpointManager(str(tmp_path / "a"), keep=10,
                                async_save=True)
        snapshots = []
        for step in (1, 2, 3):
            _train(net, opt, seed=step)
            mgr.save(step, model=net, optimizer=opt)
            snapshots.append(np.asarray(net.weight.numpy()).copy())
            _train(net, opt, seed=100 + step)   # mutate AFTER snapshot
        mgr.wait()
        assert mgr.latest_step() == 3
        for step, want in zip((1, 2, 3), snapshots):
            net2, opt2 = _make_state(seed=3)
            mgr2 = CheckpointManager(str(tmp_path / "a"))
            assert mgr2.restore(model=net2, optimizer=opt2,
                                step=step) == step
            # point-in-time snapshot: training past save() didn't leak in
            np.testing.assert_array_equal(
                np.asarray(net2.weight.numpy()), want)
        # publish order follows save order (seq strictly increasing)
        seqs = [int((tmp_path / "a" / f"step_{s}" / "save_seq").read_text())
                for s in (1, 2, 3)]
        assert seqs == sorted(seqs)
        assert checkpoint_stats()["async_saves"] >= 3

    def test_async_snapshot_survives_buffer_donation(self, tmp_path):
        """The donated fused optimizer step DELETES param/moment buffers
        on the next update; an async snapshot must not alias them.
        Simulated by hard-deleting every live array right after save()."""
        net, opt = _make_state()
        want = {i: np.asarray(p.numpy()).copy()
                for i, p in enumerate(net.parameters())}
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(1, model=net, optimizer=opt)
        for p in net.parameters():
            p.value.delete()               # what donation does under jit
        mgr.wait()                         # writer must not touch them
        net2, opt2 = _make_state(seed=11)
        mgr2 = CheckpointManager(str(tmp_path))
        assert mgr2.restore(model=net2, optimizer=opt2) == 1
        for i, p in enumerate(net2.parameters()):
            np.testing.assert_array_equal(np.asarray(p.numpy()), want[i])

    def test_explicit_corrupt_step_unreadable_seq_still_older(
            self, tmp_path):
        """Even when the corrupt dir's own save_seq is the unreadable
        file, rollback falls back to a step BELOW the request — never
        the newer state being rolled back from."""
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path), keep=10)
        mgr.save(3, model=net)
        mgr.save(5, model=net)
        mgr.save(9, model=net)
        (tmp_path / "step_5" / "save_seq").write_bytes(b"not a number")
        with pytest.warns(RuntimeWarning):
            assert mgr.restore(model=net, step=5) == 3    # not 9

    def test_wait_reports_every_background_failure(self, tmp_path):
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        faults.install("ckpt_truncate:file=model.pdparams,step=1;"
                       "ckpt_truncate:file=model.pdparams,step=2")
        mgr.save(1, model=net)
        mgr.save(2, model=net)
        with pytest.raises(RuntimeError, match="2 background checkpoint "
                                               "saves failed"):
            mgr.wait()
        mgr.wait()                        # drained: no stale re-raise

    def test_missing_component_is_usage_error_not_corruption(
            self, tmp_path):
        """Restoring a component the checkpoints never contained must
        raise cleanly — NOT cascade-quarantine every valid checkpoint."""
        net, opt = _make_state()
        mgr = CheckpointManager(str(tmp_path), keep=10)
        for s in (1, 2, 3):
            mgr.save(s, model=net)        # model-only checkpoints
        with pytest.raises(FileNotFoundError, match="saved without"):
            mgr.restore(model=net, optimizer=opt)
        # nothing was destroyed: all three dirs intact, none quarantined
        assert sorted(p.name for p in tmp_path.iterdir()) \
            == ["step_1", "step_2", "step_3"]
        assert mgr.restore(model=net) == 3    # model-only restore fine

    def test_restore_not_blocked_by_unrelated_save_failure(self, tmp_path):
        """A failed background SAVE must not abort an explicit rollback
        restore — it surfaces as a warning there; wait() still raises."""
        net, _ = _make_state()
        w5 = None
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(5, model=net)
        mgr.wait()
        w5 = np.asarray(net.weight.numpy()).copy()
        faults.install("ckpt_truncate:file=model.pdparams,step=9")
        mgr.save(9, model=net)             # will fail in the background
        net2, _ = _make_state(seed=11)
        with pytest.warns(RuntimeWarning, match="background checkpoint "
                                               "save failed"):
            assert mgr.restore(model=net2, step=5) == 5
        np.testing.assert_array_equal(np.asarray(net2.weight.numpy()), w5)

    def test_explicit_corrupt_only_checkpoint_raises(self, tmp_path):
        """Rollback to the only checkpoint, which is corrupt: raising
        beats returning None (None reads as 'cold start' and the caller
        would overwrite the run being rescued)."""
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(5, model=net)
        (tmp_path / "step_5" / "meta.pdstate").write_bytes(b"garbage")
        with pytest.warns(RuntimeWarning):
            with pytest.raises(RuntimeError, match="no earlier"):
                mgr.restore(model=net, step=5)

    def test_readonly_drain_keeps_errors_for_wait(self, tmp_path):
        """latest_step() warns about a failed background save but must
        not swallow it — the user's explicit wait() still raises."""
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        faults.install("ckpt_truncate:file=model.pdparams,step=1")
        mgr.save(1, model=net)
        with pytest.warns(RuntimeWarning, match="background checkpoint"):
            assert mgr.latest_step() is None
        with pytest.raises(RuntimeError, match="injected writer crash"):
            mgr.wait()

    def test_restore_missing_explicit_step_clean_error(self, tmp_path):
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, model=net)
        with pytest.raises(FileNotFoundError, match="available steps"):
            mgr.restore(model=net, step=99)
        assert (tmp_path / "step_1").is_dir()    # nothing quarantined

    def test_async_snapshot_decouples_host_buffers(self, tmp_path):
        """Non-jax mutable leaves (numpy running stats, nested dicts in
        extra) must be value-captured at save() time, not serialized by
        reference after the training loop mutated them."""
        net, _ = _make_state()
        stats = np.zeros(3, np.float32)
        metrics = {"best_loss": 1.0}
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(1, model=net, extra={"stats": stats.copy(),
                                      "metrics": metrics})
        # simulate save() being handed LIVE objects instead
        mgr.save(2, model=net, extra={"stats": stats,
                                      "metrics": metrics})
        stats += 99.0                     # training loop mutates in place
        metrics["best_loss"] = 0.5
        mgr.wait()
        mgr.restore(model=net, step=2)
        np.testing.assert_array_equal(mgr.last_extra["stats"],
                                      np.zeros(3, np.float32))
        assert mgr.last_extra["metrics"]["best_loss"] == 1.0

    def test_injected_midwrite_truncation_never_publishes(self, tmp_path):
        """ckpt_truncate without publish=1 is a writer crash: the tmp dir
        is abandoned and the previous checkpoint stays latest."""
        net, _ = _make_state()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, model=net)
        faults.install("ckpt_truncate:file=model.pdparams,step=2")
        with pytest.raises(RuntimeError, match="injected writer crash"):
            mgr.save(2, model=net)
        assert not (tmp_path / "step_2").exists()
        assert (tmp_path / "step_2.tmp").exists()     # the crash debris
        assert mgr.latest_step() == 1
        # an async manager surfaces the same crash at wait()
        mgr2 = CheckpointManager(str(tmp_path), async_save=True)
        faults.install("ckpt_truncate:file=model.pdparams,step=3")
        mgr2.save(3, model=net)
        with pytest.raises(RuntimeError, match="injected writer crash"):
            mgr2.wait()
        assert mgr2.latest_step() == 1

    def test_injected_published_truncation_quarantined(self, tmp_path):
        """ckpt_truncate with publish=1 models a torn write on a
        non-atomic filesystem: the corrupt dir IS published, then caught
        by digest verify and quarantined at restore."""
        net, _ = _make_state()
        w_before = np.asarray(net.weight.numpy()).copy()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, model=net)
        faults.install("ckpt_truncate:file=model.pdparams,step=2,publish=1")
        mgr.save(2, model=net)
        assert (tmp_path / "step_2").exists()
        with pytest.warns(RuntimeWarning, match="quarantined"):
            assert mgr.restore(model=net) == 1
        np.testing.assert_array_equal(
            np.asarray(net.weight.numpy()), w_before)


# ------------------------------------------------- dataloader resume ----

class TestDataLoaderResume:
    def _loader(self, n=12, batch_size=2, **kw):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import TensorDataset
        data = paddle.to_tensor(
            np.arange(n * 3, dtype=np.float32).reshape(n, 3))
        return DataLoader(TensorDataset([data]), batch_size=batch_size,
                          **kw)

    def test_state_roundtrip_mid_epoch(self):
        loader = self._loader()
        it = iter(loader)
        consumed = [next(it) for _ in range(3)]
        state = loader.state_dict()
        assert state["epoch"] == 0 and state["batch_index"] == 3

        loader2 = self._loader()
        loader2.set_state_dict(state)
        rest = [b for b in loader2]
        full = [b for b in self._loader()]
        assert len(rest) == len(full) - 3
        for got, want in zip(rest, full[3:]):
            np.testing.assert_array_equal(np.asarray(got[0].numpy()),
                                          np.asarray(want[0].numpy()))

    def test_epoch_counter_rolls(self):
        loader = self._loader()
        for _ in loader:
            pass
        st = loader.state_dict()
        assert st["epoch"] == 1 and st["batch_index"] == 0
        for _ in loader:
            pass
        assert loader.state_dict()["epoch"] == 2

    def test_threaded_loader_resumes(self):
        loader = self._loader(num_workers=2, use_native_ring=False)
        it = iter(loader)
        next(it), next(it)
        state = loader.state_dict()
        loader2 = self._loader(num_workers=2, use_native_ring=False)
        loader2.set_state_dict(state)
        rest = [np.asarray(b[0].numpy()) for b in loader2]
        full = [np.asarray(b[0].numpy()) for b in self._loader()]
        assert len(rest) == len(full) - 2
        np.testing.assert_array_equal(rest[0], full[2])

    def test_shuffled_epoch_replays_same_order(self):
        """Resume state carries the RNG as of EPOCH START: the resumed
        epoch re-draws the interrupted epoch's permutation, so the skip
        lands on exactly the batches not yet consumed."""
        np.random.seed(77)
        loader = self._loader(shuffle=True)
        it = iter(loader)
        first = [np.asarray(next(it)[0].numpy()) for _ in range(3)]
        state = loader.state_dict()
        # what the interrupted epoch WOULD have yielded next
        rest_expected = [np.asarray(b[0].numpy()) for b in it]

        np.random.seed(12345)            # a crash loses the live stream
        loader2 = self._loader(shuffle=True)
        loader2.set_state_dict(state)
        rest = [np.asarray(b[0].numpy()) for b in loader2]
        assert len(rest) == len(rest_expected)
        for got, want in zip(rest, rest_expected):
            np.testing.assert_array_equal(got, want)
        # and nothing consumed pre-crash is replayed
        for got in rest:
            for seen in first:
                assert not np.array_equal(got, seen)

    def test_between_epoch_state_is_not_stale(self):
        """state_dict() at an epoch BOUNDARY must capture the live RNG
        stream, not the finished epoch's start — a resumed next epoch
        draws a fresh permutation, same as an uninterrupted run."""
        np.random.seed(5)
        loader = self._loader(shuffle=True)
        epoch1 = [np.asarray(b[0].numpy()) for b in loader]
        state = loader.state_dict()
        epoch2_uninterrupted = [np.asarray(b[0].numpy()) for b in loader]

        np.random.seed(5)
        loader2 = self._loader(shuffle=True)
        _ = [b for b in loader2]          # replay epoch 1
        loader2.set_state_dict(state)
        epoch2_resumed = [np.asarray(b[0].numpy()) for b in loader2]
        for got, want in zip(epoch2_resumed, epoch2_uninterrupted):
            np.testing.assert_array_equal(got, want)
        # and it is NOT a repeat of epoch 1
        assert not all(np.array_equal(a, b)
                       for a, b in zip(epoch2_resumed, epoch1))

    def test_state_dict_between_iter_and_first_next(self):
        """iter() resets the position eagerly: a checkpoint taken before
        the new epoch's first batch must not report the abandoned
        previous epoch's batch index."""
        loader = self._loader()
        it = iter(loader)
        for _ in range(3):
            next(it)
        it2 = iter(loader)                 # abandon epoch, start fresh
        assert loader.state_dict()["batch_index"] == 0
        next(it2)
        assert loader.state_dict()["batch_index"] == 1

    def test_state_dict_after_set_state_dict_keeps_offset(self):
        """A checkpoint taken right after restore (before the next batch
        is drawn) must carry the restored position forward, not report
        batch 0 and double-train the replayed batches on the NEXT
        resume."""
        loader = self._loader()
        loader.set_state_dict({"epoch": 3, "batch_index": 4,
                               "np_rng_state": None})
        st = loader.state_dict()
        assert st["epoch"] == 3 and st["batch_index"] == 4

    def test_manager_captures_loader_state(self, tmp_path):
        net, _ = _make_state()
        loader = self._loader()
        it = iter(loader)
        next(it)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, model=net, dataloader=loader)
        loader2 = self._loader()
        assert mgr.restore(model=net, dataloader=loader2) == 1
        assert loader2._resume_skip == 1


# ------------------------------------------------- collective watchdog ----

class _FakeKVClient:
    """Stands in for jaxlib's DistributedRuntimeClient: rank 0's view of
    a 2-process world where rank 1 died before contributing."""

    def __init__(self, fail_sets=0):
        self.store = {}
        self.barrier_calls = 0
        self.set_calls = 0
        self._fail_sets = fail_sets

    def key_value_set(self, key, val):
        self.set_calls += 1
        if self._fail_sets > 0:
            self._fail_sets -= 1
            raise RuntimeError("UNAVAILABLE: coordination service hiccup")
        self.store[key] = val

    def blocking_key_value_get(self, key, timeout_ms):
        if key in self.store:
            return self.store[key]
        raise RuntimeError("DEADLINE_EXCEEDED: key not found in time")

    def wait_at_barrier(self, name, timeout_ms, *a):
        self.barrier_calls += 1
        raise RuntimeError(
            f"DEADLINE_EXCEEDED: barrier {name} timed out waiting for "
            "tasks")

    def key_value_delete(self, key):
        self.store.pop(key, None)


class TestCollectiveWatchdog:
    def test_timeout_diagnoses_missing_ranks(self, monkeypatch):
        from paddle_tpu.distributed import collective
        client = _FakeKVClient()
        monkeypatch.setattr(collective, "_kv_world",
                            lambda: (client, 2, 0))
        monkeypatch.setenv("PADDLE_COLLECTIVE_TIMEOUT", "1")
        before = collective.watchdog_stats()["collective_timeouts"]
        with pytest.raises(collective.CollectiveTimeout) as ei:
            collective._kv_allgather(np.ones(3), op="dp_bucket_all_reduce",
                                     bucket=2)
        msg = str(ei.value)
        assert "dp_bucket_all_reduce" in msg
        assert "bucket 2" in msg
        assert "[0]" in msg and "missing [1]" in msg   # ranks seen: us only
        assert "PADDLE_COLLECTIVE_TIMEOUT" in msg
        assert collective.watchdog_stats()["collective_timeouts"] \
            == before + 1

    def test_transient_kv_failures_retried(self, monkeypatch):
        from paddle_tpu.distributed import collective
        client = _FakeKVClient(fail_sets=2)
        before = collective.watchdog_stats()["kv_retries"]
        out = collective._kv_call(client, "key_value_set", "k", "v")
        assert out is None and client.store["k"] == "v"
        assert client.set_calls == 3
        assert collective.watchdog_stats()["kv_retries"] == before + 2

    def test_injected_kv_fault_absorbed_by_retry(self, monkeypatch):
        from paddle_tpu.distributed import collective
        faults.install("kv_fail:nth=1,op=key_value_set")
        client = _FakeKVClient()
        collective._kv_call(client, "key_value_set", "k2", "v2")
        assert client.store["k2"] == "v2"
        assert faults.fault_stats()["faults_fired"] >= 1

    def test_kv_retries_bounded(self, monkeypatch):
        from paddle_tpu.distributed import collective
        monkeypatch.setenv("PADDLE_KV_RETRIES", "2")
        client = _FakeKVClient(fail_sets=10)
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            collective._kv_call(client, "key_value_set", "k", "v")
        assert client.set_calls == 3      # 1 try + 2 retries

    def test_retry_exhaustion_mid_rendezvous_is_collective_timeout(
            self, monkeypatch):
        """PADDLE_KV_RETRIES exhausted on a transient coordinator failure
        DURING a rendezvous must surface as a diagnosable
        CollectiveTimeout naming op/group/ranks — not hang, and not leak
        the bare UNAVAILABLE KV error up through the training loop."""
        from paddle_tpu.distributed import collective

        class _Flaky(_FakeKVClient):
            # barrier passes; the per-rank GETs are persistently flaky —
            # the retry loop must exhaust and the wrapper must convert
            def wait_at_barrier(self, name, timeout_ms, *a):
                self.barrier_calls += 1

            def blocking_key_value_get(self, key, timeout_ms):
                raise RuntimeError("UNAVAILABLE: coordinator restarting")

        client = _Flaky()
        monkeypatch.setattr(collective, "_kv_world",
                            lambda: (client, 2, 0))
        monkeypatch.setenv("PADDLE_KV_RETRIES", "1")
        monkeypatch.setenv("PADDLE_COLLECTIVE_TIMEOUT", "1")
        before = collective.watchdog_stats()["collective_timeouts"]
        with pytest.raises(collective.CollectiveTimeout) as ei:
            collective._kv_allgather(np.ones(2), op="fleet_gather",
                                     group=None)
        msg = str(ei.value)
        assert "fleet_gather" in msg                     # names the op
        assert "WORLD" in msg                            # names the group
        assert "2" in msg                                # names the world
        assert "PADDLE_KV_RETRIES exhausted" in msg      # names the cause
        assert collective.watchdog_stats()["collective_timeouts"] \
            == before + 1

    def test_retry_exhaustion_at_barrier_is_collective_timeout(
            self, monkeypatch):
        """Same contract for the plain barrier() rendezvous path."""
        from paddle_tpu.distributed import collective

        class _Flaky(_FakeKVClient):
            def wait_at_barrier(self, name, timeout_ms, *a):
                self.barrier_calls += 1
                raise RuntimeError("UNAVAILABLE: connection reset")

        client = _Flaky()
        monkeypatch.setattr(collective, "_kv_world",
                            lambda: (client, 2, 0))
        monkeypatch.setattr(collective, "_process_count", lambda: 2)
        monkeypatch.setenv("PADDLE_KV_RETRIES", "1")
        monkeypatch.setenv("PADDLE_COLLECTIVE_TIMEOUT", "1")

        def _no_sync(name):
            raise RuntimeError("no cross-process device collectives")
        import jax.experimental.multihost_utils as mhu
        monkeypatch.setattr(mhu, "sync_global_devices", _no_sync)
        with pytest.raises(collective.CollectiveTimeout,
                           match="PADDLE_KV_RETRIES exhausted"):
            collective.barrier()
        assert client.barrier_calls == 2     # 1 try + 1 retry, then raise

    def test_nontransient_kv_error_stays_bare(self, monkeypatch):
        """A NON-transient mid-rendezvous failure (a real bug, e.g. a
        pickling error) must keep its own type — wrapping it as a
        timeout would misdirect the operator at a dead rank that
        doesn't exist."""
        from paddle_tpu.distributed import collective

        class _Broken(_FakeKVClient):
            def wait_at_barrier(self, name, timeout_ms, *a):
                raise AttributeError("client lost its barrier method")

        monkeypatch.setattr(collective, "_kv_world",
                            lambda: (_Broken(), 2, 0))
        monkeypatch.setenv("PADDLE_COLLECTIVE_TIMEOUT", "1")
        with pytest.raises(AttributeError):
            collective._kv_allgather(np.ones(2), op="allgather")


# ------------------------------------------------------ bootstrap retry ----

class TestBootstrapRetry:
    def _arm(self, monkeypatch):
        from paddle_tpu import _dist_bootstrap as boot
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:1")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("PADDLE_BOOTSTRAP_BACKOFF", "0.01")
        monkeypatch.setattr(boot, "_done", [False])
        return boot

    def test_transient_failures_retried_until_success(self, monkeypatch):
        import jax
        boot = self._arm(monkeypatch)
        calls = []

        def fake_init(**kw):
            calls.append(kw)
            if len(calls) < 3:
                raise RuntimeError("connection refused: coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
        before = boot.bootstrap_stats()["bootstrap_retries"]
        boot.maybe_init_distributed()
        assert len(calls) == 3
        assert boot.bootstrap_stats()["bootstrap_retries"] == before + 2

    def test_timeout_raises_actionable(self, monkeypatch):
        import jax
        boot = self._arm(monkeypatch)
        monkeypatch.setenv("PADDLE_BOOTSTRAP_TIMEOUT", "0.3")

        def fake_init(**kw):
            raise RuntimeError("DEADLINE_EXCEEDED: barrier timed out")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
        with pytest.raises(RuntimeError,
                           match="PADDLE_BOOTSTRAP_TIMEOUT"):
            boot.maybe_init_distributed()

    def test_failed_bootstrap_stays_retryable(self, monkeypatch):
        """A raised bootstrap must NOT latch the done flag: a caller that
        catches the timeout and retries once the coordinator is up must
        really connect — a silent no-op would leave a world of 1 and
        divergent replicas."""
        import jax
        boot = self._arm(monkeypatch)
        monkeypatch.setenv("PADDLE_BOOTSTRAP_TIMEOUT", "0.05")
        calls = []

        def failing(**kw):
            calls.append(kw)
            raise RuntimeError("connection refused: coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", failing)
        monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
        with pytest.raises(RuntimeError):
            boot.maybe_init_distributed()
        n_failed = len(calls)

        def succeeding(**kw):
            calls.append(kw)

        monkeypatch.setattr(jax.distributed, "initialize", succeeding)
        boot.maybe_init_distributed()      # retry really connects
        assert len(calls) == n_failed + 1
        boot.maybe_init_distributed()      # now latched: no-op
        assert len(calls) == n_failed + 1

    def test_backend_already_up_raises_immediately(self, monkeypatch):
        import jax
        boot = self._arm(monkeypatch)
        calls = []

        def fake_init(**kw):
            calls.append(kw)
            raise RuntimeError(
                "jax.distributed.initialize() must be called before any "
                "JAX computations are executed.")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        with pytest.raises(RuntimeError, match="clean interpreter"):
            boot.maybe_init_distributed()
        assert len(calls) == 1            # no retry on misconfiguration


# ------------------------------------------------ multi-process e2e ----

@pytest.mark.slow          # ~40s subprocess e2e; tier-1 budget
def test_multiprocess_kill_recovery(tmp_path):
    """The tentpole e2e in miniature: 2 supervised DP workers, rank 1
    killed mid-step by the fault registry, group relaunched, training
    resumed from the last published async checkpoint, final params match
    an uninterrupted single-process run to 1e-6."""
    from paddle_tpu.distributed.launch import supervise
    from paddle_tpu.testing.env import clean_cpu_env

    env = clean_cpu_env(REPO, device_count=1)
    env["PADDLE_COLLECTIVE_TIMEOUT"] = "30"
    env.pop("PADDLE_FAULTS", None)
    steps = 5

    def argv(tag):
        return ["-m", "paddle_tpu.testing.recovery_worker",
                "--ckpt", str(tmp_path / tag / "ckpt"),
                "--out", str(tmp_path / tag / "out"),
                "--steps", str(steps)]

    ref = supervise(argv("ref"), nprocs=1, env_base=env)
    assert ref["rc"] == 0, ref

    chaos_env = dict(env)
    chaos_env["PADDLE_FAULTS"] = "kill:step=3,rank=1,restart=0,code=43"
    summary = supervise(argv("chaos"), nprocs=2, env_base=chaos_env,
                        log_dir=str(tmp_path / "logs"),
                        max_restarts=2, backoff=0.2)
    assert summary["rc"] == 0, summary
    assert summary["restarts_used"] == 1
    assert summary["incidents"][0]["rank"] == 1
    assert summary["incidents"][0]["exit_code"] == 43

    out = tmp_path / "chaos" / "out"
    resumed = sorted(p.name for p in out.iterdir()
                     if p.name.startswith("resumed_1"))
    assert resumed, list(out.iterdir())
    marker = json.loads((out / resumed[0]).read_text())
    assert 1 <= marker["resumed_step"] <= 3     # from a PUBLISHED ckpt
    assert marker["time"] >= summary["incidents"][0]["time"]

    ref_p = np.load(tmp_path / "ref" / "out" / "params_rank0.npz")
    for r in range(2):                          # both ranks converged
        got = np.load(out / f"params_rank{r}.npz")
        for k in ref_p.files:
            np.testing.assert_allclose(got[k], ref_p[k], atol=1e-6)
    # per-worker logs captured across BOTH incarnations
    assert (tmp_path / "logs" / "worker0.log").exists()
    assert (tmp_path / "logs" / "worker1.log").exists()
