"""Expert-parallel MoE serving e2e (ISSUE 20).

The MoE serving contract: top-1 capacity-factor routing is traced
IN-GRAPH (models/gpt.py's _moe_ffn) so the traffic's routing mix is an
operand, never a recompile — two disjoint traffic mixes run through
the SAME decode executable — and the [L, E, ...] expert weights shard
WHOLE experts over the 'tp' mesh axis (gpt_hybrid.param_specs), so
adding ranks adds expert capacity without touching the program.

Parity caveat, load-bearing for every assertion here: prefill computes
expert capacity over the PADDED bucket width, so token parity against
``models.gpt.generate`` (which never pads) is only guaranteed when no
router overflow occurs — the honest unsharded reference is a
SINGLE-DEVICE engine with identical bucketing, which these tests use.
The one generate-vs-engine check pins its prompt length to a bucket
boundary, where padding is zero and the capacity math coincides.

Everything here is ``slow``: tier-1 keeps the MoE gates covered by
construction-time validation (divisibility, quant refusal) which runs
in seconds inside this module's cheap tests but rides the slow marker
with the rest to protect the tier-1 clock.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def moe_model():
    import jax
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                      num_heads=2, max_seq_len=64, dtype="float32",
                      use_flash=False, remat=False, moe_experts=4)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    return params, cfg


def _moe_engine(moe_model, **kw):
    from paddle_tpu.inference.serving import PagedServingEngine
    params, cfg = moe_model
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("seq_buckets", (8, 16, 32))
    kw.setdefault("batch_buckets", (1, 2))
    kw.setdefault("max_queue", 64)
    return PagedServingEngine((params, cfg), **kw)


class TestMoEServing:
    def test_two_mixes_one_executable(self, moe_model):
        """Two disjoint traffic mixes — short bursty prompts, then
        long uniform ones — decode through ONE executable with zero
        new XLA compiles between the mixes, and every token matches
        the single-device engine on the same trace."""
        from paddle_tpu.observability import metrics as obs
        sharded = _moe_engine(moe_model, tp=2)
        single = _moe_engine(moe_model)
        for eng in (sharded, single):
            eng.warmup()
        rng = np.random.RandomState(21)
        mix_a = [(rng.randint(1, 256, int(rng.randint(3, 8)))
                  .astype(np.int32), int(rng.randint(6, 10)))
                 for _ in range(5)]
        mix_b = [(rng.randint(1, 256, int(rng.randint(20, 30)))
                  .astype(np.int32), 5) for _ in range(4)]

        def run(eng, trace):
            reqs = [eng.submit(p, m) for p, m in trace]
            eng.run()
            return [list(r.tokens) for r in reqs]

        got_a = run(sharded, mix_a)
        c_between = obs.counter("compile.count").value
        got_b = run(sharded, mix_b)
        st = sharded.stats()
        assert st["decode_compiles"] == 1, st
        assert obs.counter("compile.count").value == c_between, \
            "the second traffic mix recompiled — routing leaked into " \
            "the executable"
        assert got_a == run(single, mix_a)
        assert got_b == run(single, mix_b)

    def test_expert_weights_shard_whole_experts(self, moe_model):
        """Expert parallelism, not expert slicing: at tp=2 each device
        pins 2 of the 4 expert MLPs whole — the E axis shards, H and F
        do not."""
        eng = _moe_engine(moe_model, tp=2)
        _params, cfg = moe_model
        w1 = eng.params["blocks"]["moe_w1"]          # [L, E, H, F]
        shards = w1.addressable_shards
        assert len(shards) == 2
        assert shards[0].data.shape[1] == cfg.moe_experts // 2
        assert shards[0].data.shape[2:] == w1.shape[2:]
        # the router is replicated: every rank scores all experts
        gate = eng.params["blocks"]["moe_gate_w"]
        assert gate.addressable_shards[0].data.shape[1:] == gate.shape[1:]

    def test_bucket_exact_generate_parity(self, moe_model):
        """With the prompt pinned to a bucket boundary (zero padding,
        identical capacity math) the engine matches gpt.generate."""
        import jax.numpy as jnp
        from paddle_tpu.models import gpt as G
        params, cfg = moe_model
        eng = _moe_engine(moe_model, tp=2)
        eng.warmup()
        prompt = np.arange(1, 9, dtype=np.int32)     # == seq bucket 8
        r = eng.submit(prompt, 6)
        eng.run()
        want = np.asarray(G.generate(params, cfg,
                                     jnp.asarray(prompt)[None], 6))
        assert list(np.asarray(r.tokens)) == list(want[0, len(prompt):])

    def test_divisibility_and_quant_gates(self, moe_model):
        """The construction-time refusals: experts must divide by tp
        (whole-expert sharding), and MoE has no quantized path yet."""
        import jax
        from paddle_tpu.models import gpt as G
        params, cfg = moe_model
        from dataclasses import replace
        cfg3 = replace(cfg, moe_experts=3)
        params3 = G.init_params(cfg3, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="must divide by tp"):
            _moe_engine((params3, cfg3), tp=2)
        with pytest.raises(ValueError, match="no quantized serving"):
            _moe_engine(moe_model, quant="int8")
