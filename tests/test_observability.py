"""Unified telemetry layer (ISSUE 4): metrics registry concurrency and
exports, StepTimer span nesting + chrome-trace boundaries, the XLA
compile hook, cross-rank aggregation (fake KV store, straggler
thresholds, 2-process e2e) and the registry-view contract behind
``profiler.fast_path_summary()``."""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.observability import (StepTimer, aggregate, metrics,
                                      timeline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off():
    """Each test starts with the event log unconfigured; configure(tmp)
    inside a test is undone here."""
    timeline.configure(None)
    yield
    timeline.configure(None)


# ------------------------------------------------------------ registry ----

class TestRegistry:
    def test_threaded_counter_increments_lose_nothing(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("t.count")
        fam = reg.stats_family("t", {"hits": 0})

        def work():
            for _ in range(2000):
                c.inc()
                fam.inc("hits")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 16000
        assert fam["hits"] == 16000

    def test_labels_key_distinct_series(self):
        reg = metrics.MetricsRegistry()
        reg.counter("req", op="a").inc(2)
        reg.counter("req", op="b").inc(5)
        snap = reg.snapshot()
        assert snap['req{op="a"}'] == 2
        assert snap['req{op="b"}'] == 5

    def test_type_collision_raises(self):
        reg = metrics.MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_histogram_percentile_math(self):
        reg = metrics.MetricsRegistry()
        h = reg.histogram("lat")
        for v in range(1, 101):
            h.observe(float(v))
        # nearest-rank percentiles over the raw observations
        assert h.percentile(50) == 50.0
        assert h.percentile(95) == 95.0
        assert h.percentile(100) == 100.0
        assert h.count == 100 and h.min == 1.0 and h.max == 100.0
        assert h.mean == pytest.approx(50.5)
        s = h.summary()
        assert s["p50"] == 50.0 and s["p95"] == 95.0

    def test_histogram_reservoir_is_bounded(self):
        reg = metrics.MetricsRegistry()
        h = reg.histogram("lat")
        for v in range(10000):
            h.observe(float(v))
        assert h.count == 10000
        assert len(h._reservoir) <= 4096
        # rolling window: old observations age out, recent ones dominate
        assert h.percentile(50) > 4000

    def test_stats_family_is_a_registry_view(self):
        reg = metrics.MetricsRegistry()
        fam = reg.stats_family("redu", {"launched": 0})
        fam["launched"] += 3
        assert reg.counter("redu.launched").value == 3
        reg.counter("redu.launched").inc(2)
        assert dict(fam) == {"launched": 5}
        reg.reset("redu")
        assert fam["launched"] == 0

    def test_prometheus_export_golden(self):
        reg = metrics.MetricsRegistry()
        h = reg.histogram("latency.s", buckets=(0.5, 2.0))
        for v in (0.5, 1.0, 4.0):
            h.observe(v)
        reg.gauge("queue.depth").set(2.5)
        reg.counter("requests.total", handler="train").inc(3)
        assert reg.to_prometheus() == (
            "# TYPE latency_s histogram\n"
            'latency_s_bucket{le="0.5"} 1\n'
            'latency_s_bucket{le="2.0"} 2\n'
            'latency_s_bucket{le="+Inf"} 3\n'
            "latency_s_sum 5.5\n"
            "latency_s_count 3\n"
            "# TYPE queue_depth gauge\n"
            "queue_depth 2.5\n"
            "# TYPE requests_total counter\n"
            'requests_total{handler="train"} 3\n')

    def test_jsonl_export_golden_format(self):
        reg = metrics.MetricsRegistry()
        reg.counter("a.count").inc(4)
        reg.histogram("a.lat").observe(0.25)
        lines = reg.export_jsonl()
        assert len(lines) == 2
        recs = [json.loads(line) for line in lines]
        for rec in recs:
            assert rec["event"] == "metric"
            assert {"name", "type", "labels", "time"} <= set(rec)
        assert recs[0] == {**recs[0], "name": "a.count",
                           "type": "counter", "value": 4}
        assert recs[1]["type"] == "histogram"
        assert recs[1]["summary"]["count"] == 1

    def test_global_reset_zeroes_everything(self):
        reg = metrics.MetricsRegistry()
        reg.counter("c").inc(7)
        reg.gauge("g").set(3)
        reg.histogram("h").observe(1.0)
        reg.reset()
        assert reg.counter("c").value == 0
        assert reg.gauge("g").value == 0.0
        assert reg.histogram("h").count == 0


# ----------------------------------------------------- registry views ----

class TestLegacyViewsServedFromRegistry:
    """The old stat dicts are VIEWS, not copies: mutating either side is
    visible on the other, and fast_path_summary() serves registry
    cells."""

    def test_reducer_view(self):
        from paddle_tpu.distributed import reducer as reducer_mod
        metrics.reset("reducer")
        reducer_mod._reducer_stats["collectives_launched"] += 2
        assert metrics.families()["reducer"]["collectives_launched"] == 2
        assert profiler.reducer_stats()["collectives_launched"] == 2
        metrics.REGISTRY.counter("reducer.collectives_launched").inc()
        assert reducer_mod.reducer_stats()["collectives_launched"] == 3
        metrics.reset("reducer")

    def test_fast_path_summary_equals_registry(self):
        net = paddle.nn.Linear(4, 2)
        opt = paddle.optimizer.Momentum(0.1, parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        s = profiler.fast_path_summary()
        fams = metrics.families()
        for k, v in fams["fused_step"].items():
            assert s["fused_step"][k] == v
        for k, v in fams["dispatch_cache"].items():
            assert s["dispatch_cache"][k] == v
        # the composite faults family spans five registry families
        for fam in ("watchdog", "launch", "checkpoint", "bootstrap",
                    "faults"):
            for k, v in fams[fam].items():
                assert s["faults"][k] == v, (fam, k)

    def test_reset_helpers_deprecated_but_working(self):
        profiler._deprecated_reset_warned.discard("reset_reducer_stats")
        metrics.REGISTRY.counter("reducer.collectives_launched").inc()
        with pytest.warns(DeprecationWarning, match="metrics.reset"):
            profiler.reset_reducer_stats()
        assert profiler.reducer_stats()["collectives_launched"] == 0
        # warn-once: the second call stays silent
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("error")
            profiler.reset_reducer_stats()


# ------------------------------------------------------------ timeline ----

class TestStepTimerAndSpans:
    def test_step_records_and_span_nesting(self, tmp_path):
        timeline.configure(str(tmp_path))
        with StepTimer(name="t", tokens_per_step=64,
                       publish_interval=0) as timer:
            for _ in range(3):
                with timer.step():
                    with timer.span("forward"):
                        with timer.span("matmul"):
                            pass
                    with timer.span("backward"):
                        pass
        assert timer.steps == 3
        pct = timer.percentiles()
        assert pct["p50"] is not None and pct["p95"] >= pct["p50"]
        events = [json.loads(line) for line in
                  open(tmp_path / "events_rank0.jsonl")]
        steps = [e for e in events if e["event"] == "step"]
        spans = [e for e in events if e["event"] == "span"]
        assert len(steps) == 3
        assert steps[0]["step"] == 1 and steps[-1]["step"] == 3
        assert steps[0]["tokens_per_s"] > 0
        assert {"wall_s", "compiles", "compile_s", "collective_wait_s",
                "phases"} <= set(steps[0])
        # phase attribution: span durations land in the step record
        assert {"forward", "backward", "matmul"} \
            <= set(steps[0]["phases"])
        # nesting depth recorded: matmul sat inside forward
        inner = [s for s in spans if s["name"] == "matmul"]
        outer = [s for s in spans if s["name"] == "forward"]
        assert inner and outer
        assert inner[0]["depth"] == outer[0]["depth"] + 1

    def test_span_is_noop_when_inactive(self):
        assert timeline.span("anything") is timeline._NULL

    def test_event_log_rotates_at_cap(self, tmp_path, monkeypatch):
        timeline.configure(str(tmp_path))
        monkeypatch.setenv("PADDLE_TELEMETRY_MAX_MB", "0.0005")  # ~500B
        for i in range(40):
            timeline.emit({"event": "scalar", "name": "x", "value": i})
        assert (tmp_path / "events_rank0.jsonl.1").exists()
        # both generations parse
        for name in ("events_rank0.jsonl", "events_rank0.jsonl.1"):
            for line in open(tmp_path / name):
                json.loads(line)

    def test_compile_hook_fires_exactly_once_per_retrace(self):
        import jax
        import jax.numpy as jnp
        timeline.install_compile_hook()
        x3 = jnp.ones((3,))
        x5 = jnp.ones((5,))           # inputs built BEFORE counting
        f = jax.jit(lambda x: x * 3 + 1)
        c = metrics.counter("compile.count")
        f(x3).block_until_ready()     # warm: compiles f (maybe consts)
        n0 = c.value
        f(x3).block_until_ready()     # cache hit: no event
        assert c.value == n0
        f(x5).block_until_ready()     # retrace: exactly one event
        assert c.value == n0 + 1
        assert metrics.counter("compile.seconds").value > 0

    def test_profiler_step_spans_have_real_duration(self, tmp_path):
        p = profiler.Profiler()
        with p:
            x = paddle.to_tensor(np.ones((4, 4), np.float32))
            for _ in range(3):
                x = x * 2.0
                p.step()
        path = str(tmp_path / "trace.json")
        p.export_chrome_tracing(path)
        evs = json.load(open(path))["traceEvents"]
        marks = [e for e in evs if e["name"] == "profiler_step"]
        assert len(marks) == 3
        assert all(e["dur"] > 0 for e in marks)
        # consecutive step spans tile the timeline (close/open, no gaps
        # beyond float rounding)
        marks.sort(key=lambda e: e["ts"])
        for a, b in zip(marks, marks[1:]):
            assert b["ts"] == pytest.approx(a["ts"] + a["dur"], abs=50.0)


def test_chrome_trace_nested_training_spans(tmp_path):
    """Acceptance: a 3-step DP run's exported chrome trace contains
    nested forward/backward/allreduce/optimizer spans inside real step
    spans, plus at least one xla_compile event with nonzero duration."""
    import jax
    from jax.sharding import Mesh
    import paddle_tpu.distributed as dist

    paddle.seed(3)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 8), paddle.nn.Tanh(),
                               paddle.nn.Linear(8, 4))
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    dp = dist.DataParallel(net, mesh=mesh, bucket_size_mb=1e9)
    opt = paddle.optimizer.Momentum(0.05, parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(8, 8).astype(np.float32))

    p = profiler.Profiler()
    with p, StepTimer(name="trace", publish_interval=0) as timer:
        for _ in range(3):
            with timer.step():
                loss = (dp(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()

    path = str(tmp_path / "trace.json")
    p.export_chrome_tracing(path)
    evs = json.load(open(path))["traceEvents"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)

    steps = by_name["step"]
    assert len(steps) == 3

    def contained(inner, outers):
        return any(o["ts"] - 1 <= inner["ts"]
                   and inner["ts"] + inner["dur"] <= o["ts"] + o["dur"] + 1
                   for o in outers)

    for name in ("forward", "backward", "allreduce", "optimizer_step"):
        assert name in by_name, sorted(by_name)
        assert all(contained(e, steps) for e in by_name[name]), name
    # one collective per step, launched from the grad-ready hook while
    # backward still runs -> the allreduce span nests inside backward
    assert any(contained(e, by_name["backward"])
               for e in by_name["allreduce"])
    compiles = by_name.get("xla_compile", [])
    assert compiles and any(e["dur"] > 0 for e in compiles)


# ----------------------------------------------------------- aggregate ----

class FakeKV:
    """Dict-backed stand-in for the jax coordination-service client."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value):
        if key in self.store:
            raise RuntimeError(f"ALREADY_EXISTS: {key}")
        self.store[key] = value

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in sorted(self.store.items())
                if k.startswith(prefix)]

    def key_value_delete(self, key):
        self.store.pop(key, None)


def _snap(rank, steps, mean, wait, last_step=None, faults=None):
    return {
        "rank": rank, "time": 1000.0 + rank, "step": last_step or steps,
        "steps": steps,
        "step_wall": {"count": steps, "sum": mean * steps, "min": mean,
                      "max": mean, "mean": mean, "p50": mean,
                      "p95": mean},
        "compiles": 2, "compile_s": 0.5,
        "collective_wait_s": wait,
        "families": {"faults": faults or {}},
    }


class TestCrossRankAggregation:
    def test_publish_gather_roundtrip_on_fake_kv(self):
        kv = FakeKV()
        aggregate.publish(step=5, client=kv, rank=0)
        aggregate.publish(step=6, client=kv, rank=1)
        aggregate.publish(step=7, client=kv, rank=1)   # newer seq wins
        snaps = aggregate.gather(client=kv)
        assert [s["rank"] for s in snaps] == [0, 1]
        assert snaps[1]["step"] == 7
        assert "families" in snaps[0] and "step_wall" in snaps[0]
        # stale sequence keys were reclaimed (bounded store)
        rank1_keys = [k for k in kv.store if "/r1/" in k]
        assert len(rank1_keys) == 1

    def test_merge_names_per_rank_step_times_and_skew(self):
        report = aggregate.merge([_snap(0, 10, 0.1, 0.0),
                                  _snap(1, 8, 0.3, 0.0)])
        assert report["nranks_seen"] == 2
        assert report["step_skew"] == 2
        assert report["ranks"][0]["step_wall_mean_s"] == 0.1
        assert report["ranks"][1]["step_wall_p95_s"] == 0.3
        assert report["ranks"][1]["faults"] == {}

    def test_straggler_flagged_below_wait_threshold(self):
        # rank 1 waits ~0 while rank 0 waits 0.5s/step: rank 1 is the
        # straggler everyone stalls on
        report = aggregate.merge(
            [_snap(0, 10, 0.6, 5.0), _snap(1, 10, 0.6, 0.1)],
            straggler_gap_s=0.2)
        assert [s["rank"] for s in report["stragglers"]] == [1]
        assert report["stragglers"][0]["reason"] \
            == "collective_wait_asymmetry"
        # under the threshold: no flag
        report = aggregate.merge(
            [_snap(0, 10, 0.6, 5.0), _snap(1, 10, 0.6, 0.1)],
            straggler_gap_s=1.0)
        assert report["stragglers"] == []

    def test_straggler_warns_when_asked(self):
        with pytest.warns(RuntimeWarning, match="straggler"):
            aggregate.merge(
                [_snap(0, 10, 0.6, 5.0), _snap(1, 10, 0.6, 0.1)],
                straggler_gap_s=0.2, warn=True)

    def test_step_lag_straggler(self):
        report = aggregate.merge(
            [_snap(0, 20, 0.1, 0.0), _snap(1, 10, 0.1, 0.0)],
            step_lag=2)
        assert [s["rank"] for s in report["stragglers"]] == [1]
        assert report["stragglers"][0]["reason"] == "step_lag"

    def test_merge_from_dir_reads_fault_counters(self, tmp_path):
        snap = _snap(1, 4, 0.2, 0.0,
                     faults={"faults_fired": 3, "faults_installed": 3})
        (tmp_path / "snapshot_rank1.json").write_text(json.dumps(snap))
        report = aggregate.merge_from_dir(str(tmp_path))
        assert report["ranks"][1]["faults"]["faults.faults_fired"] == 3

    def test_spawn_two_process_aggregation_e2e(self, tmp_path):
        """2 spawned workers train under StepTimers writing into a
        shared telemetry dir; the merged report names both ranks' step
        counts and times."""
        import spawn_helper
        tdir = str(tmp_path / "telemetry")
        paddle.distributed.spawn(spawn_helper.telemetry_train,
                                 args=(tdir, 4), nprocs=2)
        report = aggregate.merge_from_dir(tdir)
        assert report["nranks_seen"] == 2
        for r in (0, 1):
            assert report["ranks"][r]["steps"] == 4
            assert report["ranks"][r]["step_wall_p50_s"] > 0
        # the report tool renders the same dir and exits 0
        import subprocess
        import sys
        env = dict(os.environ, PYTHONPATH=REPO)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"),
             tdir, "--json"], capture_output=True, env=env, timeout=120)
        assert out.returncode == 0, out.stderr.decode()
        rendered = json.loads(out.stdout.decode())
        assert rendered["nranks_seen"] == 2


def test_injected_straggler_flagged_in_merged_report(tmp_path):
    """Acceptance: a supervised 2-process CPU run with an injected
    collective_delay (testing/faults.py) on rank 1 produces a merged
    cross-rank report naming per-rank step times and flagging rank 1 as
    the straggler (its rendezvous wait is the LOW one — rank 0 sat at
    the barrier waiting for it)."""
    from paddle_tpu.distributed.launch import supervise
    from paddle_tpu.testing.env import clean_cpu_env

    tdir = str(tmp_path / "telemetry")
    env = clean_cpu_env(REPO, device_count=1)
    env["PADDLE_COLLECTIVE_TIMEOUT"] = "30"
    # delay rank 1's contribution to EVERY bucket collective by 0.35s
    env["PADDLE_FAULTS"] = \
        "collective_delay:op=dp_bucket,seconds=0.35,rank=1,repeat=1"
    argv = ["-m", "paddle_tpu.testing.recovery_worker",
            "--ckpt", str(tmp_path / "ckpt"),
            "--out", str(tmp_path / "out"), "--steps", "4"]
    summary = supervise(argv, nprocs=2, env_base=env,
                        log_dir=str(tmp_path / "logs"),
                        telemetry_dir=tdir)
    assert summary["rc"] == 0, summary
    assert summary["telemetry_dir"] == os.path.abspath(tdir)

    report = aggregate.merge_from_dir(tdir, straggler_gap_s=0.2)
    assert report["nranks_seen"] == 2
    for r in (0, 1):
        assert report["ranks"][r]["steps"] == 4
        assert report["ranks"][r]["step_wall_mean_s"] > 0
    flagged = [s for s in report["stragglers"]
               if s["reason"] == "collective_wait_asymmetry"]
    assert [s["rank"] for s in flagged] == [1], report
    # rank 0 paid the wait; the text rendering names the straggler
    text = aggregate.format_report(report)
    assert "STRAGGLERS" in text and "rank 1" in text


# ----------------------------------------------------------- callbacks ----

class TestCallbacks:
    def test_telemetry_callback_keeps_tsv_and_fills_registry(
            self, tmp_path):
        from paddle_tpu.hapi.callbacks import VisualDL
        timeline.configure(str(tmp_path / "telemetry"))
        cb = VisualDL(str(tmp_path / "logs"))
        cb.on_begin("train")
        cb.on_train_batch_end(0, {"loss": 0.5, "acc": 0.25,
                                  "tag": "skipme"})
        cb.on_train_batch_end(1, {"loss": 0.25})
        cb.on_end("train")
        tsv = (tmp_path / "logs" / "scalars.tsv").read_text().splitlines()
        assert tsv == ["1\tloss\t0.5", "1\tacc\t0.25", "2\tloss\t0.25"]
        assert metrics.gauge("train.loss").value == 0.25
        events = [json.loads(line) for line in
                  open(tmp_path / "telemetry" / "events_rank0.jsonl")]
        scalars = [e for e in events if e["event"] == "scalar"]
        assert {(e["name"], e["value"]) for e in scalars} \
            == {("loss", 0.5), ("acc", 0.25), ("loss", 0.25)}

    def test_progress_bar_callback_reports_throughput(self, capsys):
        from paddle_tpu.hapi.callbacks import ProgressBarCallback
        cb = ProgressBarCallback(log_freq=2, tokens_per_batch=256)
        cb.on_train_begin()
        for step in range(4):
            cb.on_train_batch_begin(step)
            cb.on_train_batch_end(step)
        cb.on_train_end()
        out = capsys.readouterr().out
        assert out.count("steps/s") == 2          # every log_freq batches
        assert "tokens/s" in out
        assert timeline.current_timer() is None   # timer detached


# -------------------------------------------------------------- launch ----

def test_launch_telemetry_dir_reaches_workers_and_summary(tmp_path):
    from paddle_tpu.distributed.launch import supervise
    tdir = str(tmp_path / "telemetry")
    script = tmp_path / "w.py"
    script.write_text(
        "import json, os\n"
        "print(json.dumps({'dir': os.environ['PADDLE_TELEMETRY_DIR']}))\n")
    summary = supervise([str(script)], nprocs=1, telemetry_dir=tdir)
    assert summary["rc"] == 0
    assert summary["telemetry_dir"] == os.path.abspath(tdir)
    assert os.path.isdir(tdir)
