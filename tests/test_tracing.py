"""Distributed request tracing (ISSUE 19), unit layer: the coherent
per-process clock, the event primitive's three cost tiers, deterministic
sampling, the bounded flight-recorder ring + incident dumps, synthetic
cross-process trace assembly with clock-skew correction (no fleet
boots — hand-built event streams with KNOWN skews), and the
concurrent-writer rotation contract (satellite: no torn lines, flight
dumps survive rotation).  The live end-to-end paths run in
tools/trace_smoke.sh and bench.py's trace/disagg phases.
"""
import glob
import json
import os
import threading
import time

import pytest

from paddle_tpu.observability import aggregate, timeline, tracing


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    """Every test starts untraced, unconfigured, ring empty, rate
    limiter clear — and leaves the process role the way it found it."""
    for k in ("PADDLE_TRACE", "PADDLE_TRACE_RING",
              "PADDLE_TRACE_SAMPLE", "PADDLE_TELEMETRY_DIR",
              "PADDLE_TELEMETRY_MAX_MB"):
        monkeypatch.delenv(k, raising=False)
    role_before = tracing.role()
    timeline.configure(None)
    tracing.reset_for_tests()
    yield
    timeline.configure(None)
    tracing.reset_for_tests()
    tracing.set_role(role_before)


def _trace_lines(tmp_path):
    recs = []
    for p in sorted(glob.glob(str(tmp_path / "events_rank*.jsonl"))):
        with open(p, encoding="utf-8") as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "trace":
                    recs.append(rec)
    return recs


# ------------------------------------------------------ coherent clock ----

class TestCoherentClock:
    def test_now_never_goes_backwards(self):
        stamps = [tracing.now() for _ in range(2000)]
        assert all(b >= a for a, b in zip(stamps, stamps[1:]))

    def test_now_tracks_wall_time(self):
        # same epoch as time.time() (anchor + monotonic delta); a test
        # box doesn't NTP-step mid-session, so they agree closely
        assert abs(tracing.now() - time.time()) < 5.0

    def test_seq_is_strictly_increasing_and_shared_with_events(self):
        a = tracing.seq()
        rec = tracing.event("unit_seq")
        b = tracing.seq()
        assert a < rec["seq"] < b


# ----------------------------------------------------- event primitive ----

class TestEventPrimitive:
    def test_off_path_counts_and_rings_but_writes_nothing(self, tmp_path):
        timeline.configure(str(tmp_path))          # dir on, TRACE off
        before = tracing.stats()
        rec = tracing.event("unit_off", trace_id="abc123", k=1)
        after = tracing.stats()
        assert after["events"] == before["events"] + 1
        assert after["events_emitted"] == before["events_emitted"]
        assert rec in tracing.ring_snapshot()
        assert _trace_lines(tmp_path) == []

    def test_enabled_emits_full_record(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TRACE", "1")
        timeline.configure(str(tmp_path))
        tracing.set_role("router")
        rec = tracing.event("unit_on", trace_id="cafe", request_id="r9",
                            extra_attr=7)
        lines = _trace_lines(tmp_path)
        assert len(lines) == 1
        got = lines[0]
        assert got["name"] == "unit_on" and got["trace_id"] == "cafe"
        assert got["request_id"] == "r9" and got["extra_attr"] == 7
        assert got["pid"] == os.getpid() and got["role"] == "router"
        assert got["seq"] == rec["seq"] and got["t"] == rec["t"]

    def test_event_never_raises_without_telemetry(self):
        # no dir, no TRACE: pure counter+ring path
        rec = tracing.event("unit_bare")
        assert rec["name"] == "unit_bare" and rec["t"] > 0

    def test_sampling_is_deterministic_per_trace_id(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "0.5")
        low, high = "00000001" + "0" * 8, "ffffffff" + "0" * 8
        assert tracing.sampled(low) is True        # frac ~ 0
        assert tracing.sampled(high) is False      # frac ~ 1
        assert all(tracing.sampled(low) for _ in range(10))
        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "0")
        assert not tracing.sampled(low)
        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "1.0")
        assert tracing.sampled(high)

    def test_sample_rate_gates_emission_not_counting(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TRACE", "1")
        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "0.5")
        timeline.configure(str(tmp_path))
        tracing.event("kept", trace_id="00000001deadbeef")
        tracing.event("dropped", trace_id="ffffffffdeadbeef")
        names = [r["name"] for r in _trace_lines(tmp_path)]
        assert names == ["kept"]
        ring_names = [r["name"] for r in tracing.ring_snapshot()]
        assert "dropped" in ring_names             # ring keeps both

    def test_mint_is_16_hex_and_unique(self):
        ids = {tracing.mint() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(t) == 16 and int(t, 16) >= 0 for t in ids)


# ------------------------------------------------- flight-recorder ring ----

class TestFlightRecorder:
    def test_ring_is_bounded_keeps_newest(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRACE_RING", "8")
        for i in range(20):
            tracing.event("fill", i=i)
        snap = tracing.ring_snapshot()
        assert len(snap) == 8
        assert [r["i"] for r in snap] == list(range(12, 20))
        # shrinking the knob keeps the newest tail
        monkeypatch.setenv("PADDLE_TRACE_RING", "4")
        tracing.event("fill", i=20)
        snap = tracing.ring_snapshot()
        assert len(snap) == 4 and snap[-1]["i"] == 20

    def test_ring_zero_disables_retention(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRACE_RING", "0")
        tracing.event("gone")
        assert tracing.ring_snapshot() == []

    def test_dump_writes_atomic_json_with_inflight(self, tmp_path):
        timeline.configure(str(tmp_path))
        tracing.event("pre_incident", trace_id="aa")
        path = tracing.dump("shed", inflight=["b", "a"],
                            extra={"backlog": 3})
        assert path and os.path.exists(path)
        assert os.path.basename(path).startswith("flight_shed_")
        assert not glob.glob(str(tmp_path / "*.tmp"))
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        assert payload["reason"] == "shed"
        assert payload["inflight"] == ["a", "b"]
        assert payload["extra"] == {"backlog": 3}
        assert any(r["name"] == "pre_incident" for r in payload["ring"])

    def test_dump_rate_limited_per_reason_force_bypasses(self, tmp_path):
        timeline.configure(str(tmp_path))
        assert tracing.dump("storm") is not None
        assert tracing.dump("storm") is None       # coalesced
        assert tracing.dump("other") is not None   # distinct reason
        assert tracing.dump("storm", force=True) is not None

    def test_dump_without_telemetry_dir_is_none(self):
        assert tracing.dump("nowhere", force=True) is None

    def test_dump_never_raises_on_unwritable_dir(self, tmp_path):
        blocker = tmp_path / "not_a_dir"
        blocker.write_text("file, not dir")
        timeline.configure(str(blocker))
        before = tracing.stats()["dump_errors"]
        assert tracing.dump("doomed", force=True) is None
        assert tracing.stats()["dump_errors"] == before + 1


# --------------------------------- synthetic cross-process assembly ----

def _ev(name, t, pid, role, seq, tid="t1", rid="r1", **attrs):
    rec = {"event": "trace", "name": name, "t": t, "seq": seq,
           "pid": pid, "role": role, "trace_id": tid,
           "request_id": rid}
    rec.update(attrs)
    return rec


class TestClockSkewCorrection:
    def test_offsets_recovered_from_rpc_pairs(self):
        # replica pid 2 runs 5s BEHIND the router: correction +5
        events = [
            # router sent at 10.0; replica stamped receipt at 5.001
            _ev("rpc_recv", 5.001, 2, "replica", 1, peer_sent=10.0),
            # replica replied at 5.1 (its clock); router received 10.102
            _ev("rpc_recv", 10.102, 1, "router", 2, peer_sent=5.1,
                peer_pid=2),
        ]
        off = aggregate.trace_clock_offsets(events)
        assert abs(off[2] - 5.0) < 0.1
        assert 1 not in off                        # router is reference

    def test_one_sided_bound_sits_on_it(self):
        events = [_ev("rpc_recv", 2.0, 7, "replica", 1, peer_sent=9.0)]
        off = aggregate.trace_clock_offsets(events)
        assert off[7] == 7.0                       # zero-delay choice


class TestSyntheticAssembly:
    def _disagg_events(self):
        """One disagg lifecycle across router pid1 (reference clock),
        prefill pid2 skewed -5s, decode pid3 skewed +3s — raw stamps
        would order prefill BEFORE admit and inject into next week."""
        r, p, d = [], [], []
        r.append(_ev("admit", 100.00, 1, "router", 1,
                     priority="interactive"))
        r.append(_ev("dispatch", 100.05, 1, "router", 2))
        # rpc pair pins pid2's offset at +5
        p.append(_ev("rpc_recv", 95.051, 2, "replica", 1,
                     peer_sent=100.05))
        p.append(_ev("prefill_chunk", 95.08, 2, "replica", 2))
        p.append(_ev("prefill_done", 95.10, 2, "replica", 3))
        r.append(_ev("rpc_recv", 100.151, 1, "router", 3,
                     peer_sent=95.15, peer_pid=2))
        r.append(_ev("park", 100.20, 1, "router", 4))
        r.append(_ev("ship", 100.30, 1, "router", 5))
        # rpc pair pins pid3's offset at -3
        d.append(_ev("rpc_recv", 103.301, 3, "replica", 1,
                     peer_sent=100.30))
        d.append(_ev("inject", 103.35, 3, "replica", 2))
        d.append(_ev("completion", 103.45, 3, "replica", 3))
        r.append(_ev("rpc_recv", 100.451, 1, "router", 6,
                     peer_sent=103.45, peer_pid=3))
        r.append(_ev("ack", 100.50, 1, "router", 7))
        return r + p + d

    def test_skewed_lifecycle_assembles_causally_ordered(self):
        lcs = aggregate.assemble_traces(events=self._disagg_events())
        assert len(lcs) == 1
        lc = lcs[0]
        assert lc["request_id"] == "r1"
        assert lc["priority"] == "interactive"
        assert lc["negative_spans"] == 0
        hops = lc["hops"]
        order = ["admit", "dispatch", "prefill_done", "park", "ship",
                 "inject", "completion", "ack"]
        idx = [hops.index(h) for h in order]
        assert idx == sorted(idx), hops
        # phases telescope exactly to e2e on the corrected clock
        assert abs(sum(lc["phases"].values()) - lc["e2e_s"]) < 1e-6
        assert abs(lc["e2e_s"] - 0.5) < 0.01
        assert set(lc["phases"]) == {"queue", "prefill", "parked",
                                     "inject", "decode", "ack"}

    def test_uncorrected_stamps_would_have_gone_negative(self):
        # sanity on the fixture itself: without correction the prefill
        # leg sits 5s before its dispatch — the exact artifact the
        # rpc-pair correction exists to kill
        events = [e for e in self._disagg_events()
                  if e["name"] != "rpc_recv"]
        lcs = aggregate.assemble_traces(events=events)
        assert lcs[0]["negative_spans"] > 0

    def test_unified_lifecycle_gets_service_phase(self):
        events = [
            _ev("admit", 10.0, 1, "router", 1, priority="batch"),
            _ev("dispatch", 10.2, 1, "router", 2),
            _ev("completion", 10.9, 1, "router", 3),
            _ev("ack", 11.0, 1, "router", 4),
        ]
        lc = aggregate.assemble_traces(events=events)[0]
        assert set(lc["phases"]) == {"queue", "service", "ack"}
        assert abs(sum(lc["phases"].values()) - lc["e2e_s"]) < 1e-6

    def test_attribution_rolls_up_by_priority_and_role(self):
        def lcmk(prio, q, s):
            return {"trace_id": "x", "request_id": "x",
                    "priority": prio, "negative_spans": 0,
                    "phases": {"queue": q, "service": s},
                    "e2e_s": q + s, "t0": 0.0, "hops": [],
                    "events": []}
        lcs = [lcmk("interactive", 0.1, 0.3),
               lcmk("interactive", 0.2, 0.3),
               lcmk("batch", 5.0, 0.2)]
        attr = aggregate.trace_attribution(lcs)
        assert attr["n"] == 3 and attr["negative_spans"] == 0
        assert attr["dominant_phase"] == "queue"   # batch drags mean up
        assert attr["phases"]["queue"]["role"] == "router"
        assert attr["phases"]["service"]["role"] == "unified"
        assert set(attr["by_priority"]) == {"interactive", "batch"}
        inter = attr["by_priority"]["interactive"]
        assert inter["dominant_phase"] == "service"
        assert abs(inter["phases"]["queue"]["p50"] - 0.1) < 1e-9
        assert abs(inter["phases"]["service"]["p50"] - 0.3) < 1e-9
        assert abs(attr["e2e"]["p99"] - 5.2) < 1e-9

    def test_events_from_dir_skips_torn_lines_reads_rotation(
            self, tmp_path):
        good = _ev("admit", 1.0, 1, "router", 1)
        with open(tmp_path / "events_rank0.jsonl", "w") as f:
            f.write(json.dumps(good) + "\n")
            f.write('{"event": "trace", "name": "torn')   # SIGKILL tail
        with open(tmp_path / "events_rank0.jsonl.1", "w") as f:
            f.write(json.dumps(_ev("old", 0.5, 1, "router", 0)) + "\n")
            f.write(json.dumps({"event": "serving_step"}) + "\n")
        evs = aggregate.trace_events_from_dir(str(tmp_path))
        assert sorted(e["name"] for e in evs) == ["admit", "old"]


# ------------------------------ rotation under concurrent writers ----

class TestRotationConcurrency:
    def test_no_torn_lines_and_dumps_survive_rotation(
            self, tmp_path, monkeypatch):
        """Satellite: threads hammer timeline.emit across many
        rotations of a ~4KB cap while flight dumps land concurrently —
        every surviving line (live file AND rotated generation) parses,
        and rotation never takes a flight dump with it."""
        monkeypatch.setenv("PADDLE_TELEMETRY_MAX_MB", "0.004")
        timeline.configure(str(tmp_path))
        pad = "x" * 120
        errors = []
        dump_paths = []

        def writer(wid):
            try:
                for i in range(150):
                    tracing.event("churn", trace_id=f"{wid:08x}{i:08x}",
                                  wid=wid, i=i, pad=pad)
            except Exception as e:                 # noqa: BLE001
                errors.append(e)

        def dumper():
            try:
                for i in range(10):
                    p = tracing.dump(f"mid_rotation_{i}",
                                     inflight=[f"req-{i}"], force=True)
                    if p:
                        dump_paths.append(p)
                    time.sleep(0.002)
            except Exception as e:                 # noqa: BLE001
                errors.append(e)

        monkeypatch.setenv("PADDLE_TRACE", "1")
        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(4)] + [threading.Thread(target=dumper)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

        live = glob.glob(str(tmp_path / "events_rank*.jsonl"))
        rotated = glob.glob(str(tmp_path / "events_rank*.jsonl.1"))
        assert live and rotated                    # cap actually tripped
        total = 0
        for p in live + rotated:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    rec = json.loads(line)         # torn line -> raises
                    assert rec["event"] == "trace"
                    total += 1
        assert total > 0
        # every dump filed during the churn is still on disk, intact
        assert len(dump_paths) == 10
        for p in dump_paths:
            with open(p, encoding="utf-8") as f:
                payload = json.load(f)
            assert payload["inflight"] and payload["ring"]
        # and the aggregate reader walks the churned dir without choking
        assert len(aggregate.trace_events_from_dir(str(tmp_path))) \
            == total
