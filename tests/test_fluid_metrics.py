"""fluid.metrics streaming classes incl. VOC DetectionMAP goldens."""
import numpy as np
import pytest

import paddle_tpu as paddle

fm = paddle.fluid.metrics


class TestStreaming:
    def test_precision_recall(self):
        p = fm.Precision()
        r = fm.Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7])
        labels = np.array([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert p.eval() == pytest.approx(2 / 3)    # tp=2 fp=1
        assert r.eval() == pytest.approx(2 / 3)    # tp=2 fn=1

    def test_accuracy_weighted(self):
        a = fm.Accuracy()
        a.update(0.5, 10)
        a.update(1.0, 10)
        assert a.eval() == pytest.approx(0.75)

    def test_chunk_evaluator_composes_with_chunk_eval(self):
        fl = paddle.fluid.layers
        lab = paddle.to_tensor(np.array([[0, 1, 4, 2, 3, 4]]))
        inf = paddle.to_tensor(np.array([[0, 1, 4, 2, 4, 4]]))
        _, _, _, ni, nl, nc = fl.chunk_eval(inf, lab, "IOB", 2)
        ce = fm.ChunkEvaluator()
        ce.update(ni, nl, nc)
        ce.update(ni, nl, nc)
        p, r, f1 = ce.eval()
        assert f1 == pytest.approx(0.5)

    def test_edit_distance(self):
        ed = fm.EditDistance()
        ed.update(np.array([0.0, 2.0]), 2)
        avg, err = ed.eval()
        assert avg == pytest.approx(1.0)
        assert err == pytest.approx(0.5)

    def test_auc_perfect_and_random(self):
        auc = fm.Auc()
        auc.update(np.array([0.9, 0.8, 0.2, 0.1]), np.array([1, 1, 0, 0]))
        assert auc.eval() == pytest.approx(1.0)
        auc.reset()
        auc.update(np.array([0.6, 0.6, 0.6, 0.6]), np.array([1, 0, 1, 0]))
        assert auc.eval() == pytest.approx(0.5)

    def test_composite(self):
        c = fm.CompositeMetric()
        c.add_metric(fm.Precision())
        c.add_metric(fm.Recall())
        c.update(np.array([0.9]), np.array([1]))
        assert c.eval() == [1.0, 1.0]


class TestDetectionMAP:
    def test_perfect_detections(self):
        m = fm.DetectionMAP(class_num=2)
        gt_boxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], "float32")
        gt_labels = np.array([[0, 1]])
        dets = np.array([[[0, 0.9, 0, 0, 10, 10],
                          [1, 0.8, 20, 20, 30, 30],
                          [-1, 0, 0, 0, 0, 0]]], "float32")
        m.update(dets, gt_labels, gt_boxes)
        assert m.accumulate() == pytest.approx(1.0)

    def test_false_positive_halves_ap(self):
        m = fm.DetectionMAP(class_num=1)
        gt_boxes = np.array([[[0, 0, 10, 10]]], "float32")
        gt_labels = np.array([[0]])
        # fp with the HIGHER score ranks first: precision@match = 1/2
        dets = np.array([[[0, 0.9, 50, 50, 60, 60],
                          [0, 0.8, 0, 0, 10, 10]]], "float32")
        m.update(dets, gt_labels, gt_boxes)
        assert m.accumulate() == pytest.approx(0.5)

    def test_11point_version(self):
        m = fm.DetectionMAP(class_num=1, ap_version="11point")
        gt_boxes = np.array([[[0, 0, 10, 10]]], "float32")
        gt_labels = np.array([[0]])
        dets = np.array([[[0, 0.9, 0, 0, 10, 10]]], "float32")
        m.update(dets, gt_labels, gt_boxes)
        assert m.accumulate() == pytest.approx(1.0)

    def test_duplicate_detection_is_fp(self):
        m = fm.DetectionMAP(class_num=1)
        gt_boxes = np.array([[[0, 0, 10, 10]]], "float32")
        gt_labels = np.array([[0]])
        dets = np.array([[[0, 0.9, 0, 0, 10, 10],
                          [0, 0.8, 0, 0, 10, 10]]], "float32")
        m.update(dets, gt_labels, gt_boxes)
        # second match of the same gt counts as fp; integral AP stays 1.0
        # at recall 1 reached by the first det
        assert m.accumulate() == pytest.approx(1.0)


class TestContribAmp:
    def test_mixed_precision_decorate_trains(self):
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        amp_opt = paddle.fluid.contrib.mixed_precision.decorate(opt)
        rng = np.random.RandomState(0)
        xv = rng.randn(16, 4).astype("float32")
        yv = xv.sum(1, keepdims=True).astype("float32")
        first = last = None
        for _ in range(15):
            loss = ((lin(paddle.to_tensor(xv))
                     - paddle.to_tensor(yv)) ** 2).mean()
            amp_opt.minimize(loss)
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first * 0.5

    def test_slim_quant_aliases(self):
        q = paddle.fluid.contrib.slim.quantization
        assert q.PostTrainingQuantization is not None
        assert q.QuantizationTransformPass is not None
