"""Optimizer + LR scheduler tests (SURVEY.md §4): closed-form step math and
convergence on a quadratic bowl."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as optim


def _quadratic_converges(opt_factory, steps=300, tol=1e-2):
    paddle.seed(3)
    w = paddle.to_tensor(np.array([5.0, -3.0], np.float32),
                         stop_gradient=False)
    w = paddle.Parameter(w.numpy()) if False else w
    from paddle_tpu.tensor.tensor import Parameter
    p = Parameter(np.array([5.0, -3.0], np.float32))
    opt = opt_factory([p])
    for _ in range(steps):
        loss = ((p - paddle.to_tensor([1.0, 2.0])) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(p.numpy(), [1.0, 2.0], atol=tol * 10)
    return float(loss)


def test_sgd_step_math():
    from paddle_tpu.tensor.tensor import Parameter
    p = Parameter(np.array([1.0, 2.0], np.float32))
    opt = optim.SGD(learning_rate=0.1, parameters=[p])
    ((p * p).sum()).backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1 - 0.1 * 2, 2 - 0.1 * 4],
                               rtol=1e-6)


def test_momentum_math():
    from paddle_tpu.tensor.tensor import Parameter
    p = Parameter(np.array([1.0], np.float32))
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
    (p * 3.0).sum().backward()
    opt.step()  # v = 3; p = 1 - 0.3
    opt.clear_grad()
    np.testing.assert_allclose(p.numpy(), [0.7], rtol=1e-6)
    (p * 3.0).sum().backward()
    opt.step()  # v = 0.9*3 + 3 = 5.7; p = 0.7 - 0.57
    np.testing.assert_allclose(p.numpy(), [0.13], rtol=1e-5)


def test_adam_bias_correction_first_step():
    from paddle_tpu.tensor.tensor import Parameter
    p = Parameter(np.array([1.0], np.float32))
    opt = optim.Adam(learning_rate=0.1, parameters=[p])
    (p * 0.5).sum().backward()
    opt.step()
    # first Adam step ≈ -lr * sign(g)
    np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-4)


@pytest.mark.parametrize("factory", [
    lambda ps: optim.SGD(0.1, parameters=ps),
    lambda ps: optim.Momentum(0.05, parameters=ps),
    lambda ps: optim.Adam(0.2, parameters=ps),
    lambda ps: optim.AdamW(0.2, parameters=ps, weight_decay=0.0),
    lambda ps: optim.Adamax(0.3, parameters=ps),
    lambda ps: optim.RMSProp(0.05, parameters=ps),
    lambda ps: optim.Adagrad(0.5, parameters=ps),
    lambda ps: optim.Adadelta(20.0, rho=0.9, parameters=ps),
    # LAMB's trust ratio keeps |update| ∝ |w|, so on a toy bowl it orbits the
    # optimum — accept a loose tolerance
    lambda ps: optim.Lamb(0.05, parameters=ps, lamb_weight_decay=0.0),
], ids=["sgd", "momentum", "adam", "adamw", "adamax", "rmsprop", "adagrad",
        "adadelta", "lamb"])
def test_quadratic_convergence(factory, request):
    tol = 5e-2 if request.node.callspec.id == "lamb" else 1e-2
    _quadratic_converges(factory, tol=tol)


def test_weight_decay_and_clip():
    from paddle_tpu.tensor.tensor import Parameter
    import paddle_tpu.nn as nn
    p = Parameter(np.array([10.0], np.float32))
    opt = optim.SGD(0.1, parameters=[p], weight_decay=0.1)
    (p * 0.0).sum().backward()
    opt.step()
    # g = 0 + 0.1*10 = 1 → p = 10 - 0.1
    np.testing.assert_allclose(p.numpy(), [9.9], rtol=1e-5)

    p2 = Parameter(np.array([1.0], np.float32))
    opt2 = optim.SGD(1.0, parameters=[p2],
                     grad_clip=nn.ClipGradByGlobalNorm(0.5))
    (p2 * 10.0).sum().backward()
    opt2.step()
    np.testing.assert_allclose(p2.numpy(), [0.5], rtol=1e-4)


def test_state_dict_roundtrip():
    from paddle_tpu.tensor.tensor import Parameter
    p = Parameter(np.array([1.0], np.float32), name="p0")
    opt = optim.Adam(0.1, parameters=[p])
    (p * 2).sum().backward()
    opt.step()
    sd = opt.state_dict()
    p2 = Parameter(np.array([1.0], np.float32), name="p0")
    opt2 = optim.Adam(0.1, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        opt2._accumulators["moment1"][id(p2)],
        opt._accumulators["moment1"][id(p)])


class TestLRSchedulers:
    def test_step_decay(self):
        s = optim.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(s())
            s.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_multistep_exponential(self):
        s = optim.lr.MultiStepDecay(1.0, [2, 4], gamma=0.1)
        vals = []
        for _ in range(5):
            vals.append(round(s(), 6))
            s.step()
        assert vals == [1.0, 1.0, 0.1, 0.1, 0.01]
        e = optim.lr.ExponentialDecay(1.0, 0.5)
        e.step()
        np.testing.assert_allclose(e(), 0.5)

    def test_warmup_cosine_noam(self):
        w = optim.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0,
                                  end_lr=0.1)
        first = w()
        for _ in range(10):
            w.step()
        assert first < 0.02 and abs(w() - 0.1) < 1e-6
        c = optim.lr.CosineAnnealingDecay(0.1, T_max=10)
        assert abs(c() - 0.1) < 1e-9
        for _ in range(10):
            c.step()
        assert c() < 1e-8
        n = optim.lr.NoamDecay(64, warmup_steps=100)
        lrs = [n()]
        for _ in range(200):
            n.step()
            lrs.append(n())
        assert max(lrs) == lrs[100]  # peak at warmup boundary

    def test_reduce_on_plateau(self):
        s = optim.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)
        assert s() == pytest.approx(0.05)

    def test_piecewise_lambda_poly(self):
        pw = optim.lr.PiecewiseDecay([3, 6], [0.1, 0.05, 0.01])
        assert pw() == 0.1
        lam = optim.lr.LambdaDecay(0.5, lambda e: 1.0 / (e + 1))
        assert lam() == 0.5
        poly = optim.lr.PolynomialDecay(0.1, decay_steps=10, end_lr=0.0)
        for _ in range(10):
            poly.step()
        assert poly() == pytest.approx(0.0, abs=1e-8)

    def test_optimizer_uses_scheduler(self):
        from paddle_tpu.tensor.tensor import Parameter
        p = Parameter(np.array([1.0], np.float32))
        sched = optim.lr.StepDecay(0.1, step_size=1, gamma=0.1)
        opt = optim.SGD(sched, parameters=[p])
        assert opt.get_lr() == pytest.approx(0.1)
        sched.step()
        assert opt.get_lr() == pytest.approx(0.01)


def test_amp_grad_scaler():
    from paddle_tpu.tensor.tensor import Parameter
    p = Parameter(np.array([1.0], np.float32))
    opt = optim.SGD(0.1, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    loss = (p * 3.0).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    np.testing.assert_allclose(p.numpy(), [0.7], rtol=1e-5)


def test_auto_cast_context():
    with paddle.amp.auto_cast(dtype="bfloat16"):
        from paddle_tpu.amp.auto_cast import amp_state
        assert amp_state() is not None
    from paddle_tpu.amp.auto_cast import amp_state
    assert amp_state() is None


class TestIncubateOptimizers:
    def test_lookahead_converges_and_interpolates(self):
        import paddle_tpu as paddle
        import numpy as np
        rng = np.random.RandomState(0)
        xv = rng.randn(64, 4).astype("float32")
        w_true = rng.randn(4, 1).astype("float32")
        yv = xv @ w_true
        lin = paddle.nn.Linear(4, 1)
        inner = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        opt = paddle.incubate.LookAhead(inner, alpha=0.5, k=5)
        first = last = None
        for _ in range(40):
            loss = ((lin(paddle.to_tensor(xv))
                     - paddle.to_tensor(yv)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first * 0.2, (first, last)
        # slow weights must equal the live weights right after a sync step
        assert opt._step_count % 5 == 0
        np.testing.assert_allclose(
            np.asarray(lin.weight.numpy()),
            np.asarray(opt._slow[id(lin.weight)]), atol=1e-6)

    def test_model_average_apply_restore(self):
        import paddle_tpu as paddle
        import numpy as np
        lin = paddle.nn.Linear(2, 1)
        ma = paddle.incubate.ModelAverage(
            0.15, parameters=lin.parameters(), min_average_window=10,
            max_average_window=20)
        seen = []
        for i in range(4):
            lin.weight.set_value(
                np.full((2, 1), float(i), np.float32))
            ma.step()
            seen.append(float(i))
        live = np.asarray(lin.weight.numpy()).copy()
        with ma.apply():
            avg = np.asarray(lin.weight.numpy())
            np.testing.assert_allclose(avg, np.mean(seen), atol=1e-6)
        np.testing.assert_allclose(np.asarray(lin.weight.numpy()), live)


class TestFluidLongTailOptimizers:
    def _fit(self, opt_builder, steps=40, tol=0.3):
        import paddle_tpu as paddle
        import numpy as np
        rng = np.random.RandomState(0)
        xv = rng.randn(64, 4).astype("float32")
        yv = xv @ rng.randn(4, 1).astype("float32")
        lin = paddle.nn.Linear(4, 1)
        opt = opt_builder(lin)
        first = last = None
        for _ in range(steps):
            loss = ((lin(paddle.to_tensor(xv))
                     - paddle.to_tensor(yv)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first * tol, (first, last)

    def test_decayed_adagrad_converges(self):
        from paddle_tpu.optimizer.optimizers import DecayedAdagrad
        self._fit(lambda m: DecayedAdagrad(
            0.2, parameters=m.parameters()))

    def test_ftrl_converges(self):
        from paddle_tpu.optimizer.optimizers import Ftrl
        self._fit(lambda m: Ftrl(0.5, parameters=m.parameters()),
                  steps=80)

    def test_lars_converges(self):
        import paddle_tpu as paddle
        import numpy as np
        from paddle_tpu.optimizer.optimizers import LarsMomentum
        # LARS trust ratio caps |update| at ~coeff*lr*||w|| per step, so
        # it pairs with LARGE base lrs; biases (zero-norm) are excluded
        # from LARS param lists, reference practice
        rng = np.random.RandomState(0)
        xv = rng.randn(64, 4).astype("float32")
        yv = xv @ rng.randn(4, 1).astype("float32")
        lin = paddle.nn.Linear(4, 1, bias_attr=False)
        opt = LarsMomentum(20.0, momentum=0.5,
                           parameters=lin.parameters())
        first = last = None
        for _ in range(150):
            loss = ((lin(paddle.to_tensor(xv))
                     - paddle.to_tensor(yv)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first * 0.3, (first, last)

    def test_dpsgd_runs_and_descends(self):
        from paddle_tpu.optimizer.optimizers import Dpsgd
        self._fit(lambda m: Dpsgd(0.05, clip=5.0, batch_size=64.0,
                                  sigma=0.01, parameters=m.parameters()),
                  steps=80, tol=0.7)

    def test_ftrl_l1_sparsifies(self):
        import paddle_tpu as paddle
        import numpy as np
        from paddle_tpu.optimizer.optimizers import Ftrl
        lin = paddle.nn.Linear(8, 1)
        opt = Ftrl(0.5, l1=5.0, parameters=lin.parameters())
        rng = np.random.RandomState(1)
        xv = rng.randn(32, 8).astype("float32")
        yv = (xv[:, :1] * 0.1).astype("float32")   # weak signal
        for _ in range(30):
            loss = ((lin(paddle.to_tensor(xv))
                     - paddle.to_tensor(yv)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        w = np.asarray(lin.weight.numpy())
        assert (np.abs(w) < 1e-6).mean() > 0.5   # strong L1 zeroes most

    def test_ema_apply_restore(self):
        import paddle_tpu as paddle
        import numpy as np
        lin = paddle.nn.Linear(2, 1)
        ema = paddle.incubate.optimizer.ExponentialMovingAverage(
            decay=0.5, parameters=lin.parameters())
        for i in range(1, 4):
            lin.weight.set_value(np.full((2, 1), float(i), np.float32))
            ema.update()
        live = np.asarray(lin.weight.numpy()).copy()
        with ema.apply():
            # zero-init bias-corrected EMA of [1, 2, 3] at decay .5:
            # ema = .125*1? -> compute: e1=.5*0+.5*1=.5; e2=.25+.5*2=1.25;
            # e3=.625+.5*3=2.125 ; corr=1-.5^3=.875 -> 2.4286
            np.testing.assert_allclose(
                np.asarray(lin.weight.numpy())[0, 0], 2.125 / 0.875,
                atol=1e-6)
        np.testing.assert_allclose(np.asarray(lin.weight.numpy()), live)

    def test_fluid_spellings_exist(self):
        import paddle_tpu as paddle
        fo = paddle.fluid.optimizer
        for n in ("SGD Momentum Adam Adagrad Adamax Adadelta RMSProp Lamb "
                  "DecayedAdagrad Ftrl Dpsgd LarsMomentum "
                  "SGDOptimizer LarsMomentumOptimizer FtrlOptimizer "
                  "LookaheadOptimizer ModelAverage "
                  "ExponentialMovingAverage PipelineOptimizer "
                  "RecomputeOptimizer").split():
            assert hasattr(fo, n), n

    def test_recompute_optimizer_static_trains(self):
        import paddle_tpu as paddle
        import numpy as np
        fluid = paddle.fluid
        paddle.enable_static()
        try:
            prog, start = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, start):
                x = fluid.layers.data("x", [4])
                y = fluid.layers.data("y", [1])
                h = fluid.layers.fc(x, 16, activation="relu")
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square_error_cost(
                        fluid.layers.fc(h, 1), y))
                opt = fluid.optimizer.RecomputeOptimizer(
                    fluid.optimizer.SGD(0.05))
                opt.minimize(loss)
                exe = fluid.Executor()
                rng = np.random.RandomState(0)
                xv = rng.randn(16, 4).astype("float32")
                yv = xv.sum(1, keepdims=True).astype("float32") * 0.3
                first = last = None
                for _ in range(20):
                    (lv,) = exe.run(prog, feed={"x": xv, "y": yv},
                                    fetch_list=[loss])
                    first = first if first is not None else float(lv)
                    last = float(lv)
            assert last < first * 0.5
        finally:
            paddle.disable_static()
