"""WordPiece tokenizer: native C++ vs pure-Python parity + goldens."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text.tokenizer import FullTokenizer, _basic_tokenize


VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "brown",
         "fox", "jump", "##s", "##ed", "##ing", "over", "lazy", "dog",
         "un", "##aff", "##able", ",", ".", "!", "a", "b", "c"]


@pytest.fixture()
def vocab_file(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return str(p)


class TestWordpiece:
    def test_golden_tokenization(self, vocab_file):
        tok = FullTokenizer(vocab_file, use_native=False)
        assert tok.tokenize("The quick brown fox jumps!") == \
            ["the", "quick", "brown", "fox", "jump", "##s", "!"]
        assert tok.tokenize("unaffable") == ["un", "##aff", "##able"]
        # unknown word -> [UNK] as a whole
        assert tok.tokenize("zzz") == ["[UNK]"]
        # punctuation isolation
        assert tok.tokenize("fox,dog.") == ["fox", ",", "dog", "."]

    def test_case_handling(self, vocab_file):
        lower = FullTokenizer(vocab_file, do_lower_case=True,
                              use_native=False)
        keep = FullTokenizer(vocab_file, do_lower_case=False,
                             use_native=False)
        assert lower.encode("THE") == [VOCAB.index("the")]
        assert keep.encode("THE") == [VOCAB.index("[UNK]")]

    def test_native_matches_python(self, vocab_file):
        from paddle_tpu import runtime
        if not runtime.is_available():
            pytest.skip("no native runtime")
        nat = FullTokenizer(vocab_file, use_native=True)
        py = FullTokenizer(vocab_file, use_native=False)
        assert nat._native is not None
        texts = [
            "The quick brown fox jumps over the lazy dog!",
            "unaffable, unaffable. jumping jumped",
            "a b c abc cab",
            "",
            "  spaced   out  ",
            "punct!!!...,,",
            "mixed CASE Words",
        ]
        for s in texts:
            assert nat.encode(s) == py.encode(s), s

    def test_native_fuzz_parity(self, vocab_file):
        from paddle_tpu import runtime
        if not runtime.is_available():
            pytest.skip("no native runtime")
        nat = FullTokenizer(vocab_file, use_native=True)
        py = FullTokenizer(vocab_file, use_native=False)
        rng = np.random.RandomState(0)
        alphabet = list("abc theniqus.,!ZQ ") + ["\x1c", "\x1d", "\x1f", "\t", "\n"]
        for _ in range(200):
            s = "".join(rng.choice(alphabet)
                        for _ in range(rng.randint(0, 40)))
            assert nat.encode(s) == py.encode(s), repr(s)

    def test_duplicate_vocab_last_wins(self, tmp_path):
        p = tmp_path / "dup.txt"
        p.write_text("[UNK]\na\nb\na\n", encoding="utf-8")
        py = FullTokenizer(str(p), use_native=False)
        assert py.encode("a") == [3]       # last occurrence wins
        from paddle_tpu import runtime
        if runtime.is_available():
            nat = FullTokenizer(str(p), use_native=True)
            assert nat.encode("a") == [3]

    def test_control_char_whitespace_parity(self, vocab_file):
        from paddle_tpu import runtime
        if not runtime.is_available():
            pytest.skip("no native runtime")
        nat = FullTokenizer(vocab_file, use_native=True)
        py = FullTokenizer(vocab_file, use_native=False)
        for s in ("a\x1cb", "fox\x1ddog", "the\x1equick", "a\x1fb",
                  "a\x0bb", "a\x0cb"):
            assert nat.encode(s) == py.encode(s), repr(s)

    def test_ids_roundtrip(self, vocab_file):
        tok = FullTokenizer(vocab_file, use_native=False)
        toks = tok.tokenize("the quick fox")
        ids = tok.convert_tokens_to_ids(toks)
        assert tok.convert_ids_to_tokens(ids) == toks
