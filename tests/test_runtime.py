"""Native C++ runtime: host memory pool + data ring + DataLoader staging.

Models the reference's reader/allocator unittests (ref: paddle/fluid/
operators/reader/reader_blocking_queue_test.cc, paddle/fluid/memory/
allocation/auto_growth_best_fit_allocator_test.cc): blocking semantics,
capacity backpressure, FIFO drain on close, allocator reuse + statistics.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu import runtime

pytestmark = pytest.mark.skipif(
    not runtime.is_available(), reason="no C++ toolchain")


def test_pool_alloc_free_stats():
    pool = runtime.HostMemoryPool()
    p1 = pool.alloc(1000)        # class 1024
    p2 = pool.alloc(5000)        # class 8192
    s = pool.stats()
    assert s["alloc_count"] == 2 and s["grow_count"] == 2
    assert s["in_use"] == 1024 + 8192
    assert s["reserved"] >= s["in_use"]
    pool.free(p1)
    s = pool.stats()
    assert s["in_use"] == 8192 and s["free_count"] == 1
    # same-class realloc must reuse the cached block, not grow
    p3 = pool.alloc(900)
    s = pool.stats()
    assert s["grow_count"] == 2 and s["in_use"] == 1024 + 8192
    assert p3 == p1
    pool.free(p2)
    pool.free(p3)
    s = pool.stats()
    assert s["in_use"] == 0 and s["peak_in_use"] == 1024 + 8192
    pool.close()


def test_ring_roundtrip_multi_array():
    ring = runtime.DataRing(capacity=4)
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.arange(5, dtype=np.int64)
    assert ring.push([a, b], tag=7) == 0
    views, tag = ring.pop()
    assert tag == 7
    np.testing.assert_array_equal(views[0], a)
    np.testing.assert_array_equal(views[1], b)
    assert views[0].dtype == np.float32 and views[1].dtype == np.int64
    ring.destroy()


def test_ring_backpressure_and_fifo():
    ring = runtime.DataRing(capacity=2)
    x = np.zeros(16, np.float32)
    assert ring.push([x], 0) == 0
    assert ring.push([x], 1) == 0
    assert ring.push([x], 2, timeout_ms=50) == ring.TIMEOUT  # full
    views, tag = ring.pop()
    assert tag == 0                                          # FIFO
    assert ring.push([x], 2, timeout_ms=1000) == 0           # slot freed
    assert ring.pop()[1] == 1
    assert ring.pop()[1] == 2
    ring.destroy()


def test_ring_close_wakes_consumer_and_drains():
    ring = runtime.DataRing(capacity=4)
    ring.push([np.ones(4, np.float32)], 0)
    results = []

    def consumer():
        while True:
            got = ring.pop()
            if got is None:
                return
            results.append(got[1])

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.1)
    ring.close()
    t.join(timeout=2)
    assert not t.is_alive()
    assert results == [0]          # pushed item drained before close returns
    ring.destroy()


def test_ring_concurrent_producers():
    ring = runtime.DataRing(capacity=3)
    n = 40

    def producer(k):
        rng = np.random.RandomState(k)
        for i in range(10):
            tag = k * 10 + i
            arr = rng.randn(8, 8).astype(np.float32)
            assert ring.push([arr, np.asarray([tag])], tag) == 0

    threads = [threading.Thread(target=producer, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    got = {}
    for _ in range(n):
        views, tag = ring.pop()
        # payload integrity: embedded tag must match slab tag
        assert int(views[1][0]) == tag
        got[tag] = views[0].copy()
    for t in threads:
        t.join()
    assert set(got) == set(range(n))
    for tag, arr in got.items():
        # regenerate the k-th draw of that producer
        rng = np.random.RandomState(tag // 10)
        for _ in range(tag % 10 + 1):
            want = rng.randn(8, 8)
        np.testing.assert_allclose(arr, want.astype(np.float32))
    stats = ring.stats()
    assert stats["pushed"] == n and stats["popped"] == n
    # slabs are recycled: far fewer OS allocations than pushes
    assert stats["grow_count"] <= 8
    ring.destroy()


def test_host_memory_stats_api():
    s = runtime.host_memory_stats()
    assert set(s) >= {"reserved", "in_use", "peak_in_use", "alloc_count"}


def test_dataloader_native_ring_matches_single_thread():
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __len__(self):
            return 23

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            return (rng.randn(4, 5).astype(np.float32),
                    np.int64(i))

    ds = DS()
    ref = [b for b in DataLoader(ds, batch_size=4, num_workers=0)]
    got = [b for b in DataLoader(ds, batch_size=4, num_workers=3,
                                 use_native_ring=True)]
    assert len(ref) == len(got) == 6
    for (rx, ry), (gx, gy) in zip(ref, got):
        np.testing.assert_allclose(np.asarray(rx.numpy()),
                                   np.asarray(gx.numpy()))
        np.testing.assert_array_equal(np.asarray(ry.numpy()),
                                      np.asarray(gy.numpy()))


def test_dataloader_native_ring_propagates_worker_error():
    from paddle_tpu.io import DataLoader, Dataset

    class Bad(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom")
            return np.zeros(3, np.float32)

    with pytest.raises(ValueError, match="boom"):
        list(DataLoader(Bad(), batch_size=2, num_workers=2,
                        use_native_ring=True))


def test_native_preprocess_matches_numpy():
    from paddle_tpu import runtime
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (6, 17, 23, 3)).astype(np.uint8)
    mean = [0.485, 0.456, 0.406]
    std = [0.229, 0.224, 0.225]
    got = runtime.preprocess_images(imgs, mean, std)
    want = (imgs.astype(np.float32) / 255.0 - np.float32(mean)) \
        / np.float32(std)
    want = want.transpose(0, 3, 1, 2)
    assert got.shape == (6, 3, 17, 23) and got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_native_preprocess_single_channel_and_list():
    from paddle_tpu import runtime
    rng = np.random.RandomState(1)
    imgs = [rng.randint(0, 256, (8, 8, 1)).astype(np.uint8)
            for _ in range(3)]
    got = runtime.preprocess_images(imgs, [0.5], [0.5])
    want = np.stack([(a.astype(np.float32) / 255.0 - 0.5) / 0.5
                     for a in imgs]).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
