"""ResNet50 through the static data-parallel path (BASELINE.json
configs[1]; VERDICT r2 item 10): builds the real examples/ program on the
8-device mesh, one step decreases loss, feeds verifiably batch-sharded."""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static

# model-level heavyweight suite (full ResNet50 static step on CPU) —
# runs in the slow tier, outside the tier-1 wall-clock budget
pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))


def test_resnet50_static_dp_one_step_decreases_loss():
    from resnet50_static_dp import build_program

    paddle.enable_static()
    try:
        main_prog, startup, loss = build_program(image_size=32,
                                                 num_classes=10, lr=1e-3)
        exe = static.ParallelExecutor(main_program=main_prog)
        assert exe._mesh is not None and exe._mesh.size == 8
        exe.run(startup)
        rng = np.random.RandomState(0)
        x = rng.randn(16, 3, 32, 32).astype(np.float32)
        y = rng.randint(0, 10, (16, 1)).astype(np.int64)
        losses = []
        for _ in range(3):
            lv, = exe.run(feed={"image": x, "label": y},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
    finally:
        paddle.disable_static()


def test_parallel_executor_positional_run_keeps_fetches():
    """run(program, feed, fetch_list) Executor-style must not drop the
    fetch list."""
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 2], "float32")
            y = x * 2.0
        exe = static.ParallelExecutor()
        r, = exe.run(main, {"x": np.ones((4, 2), np.float32)}, [y])
        np.testing.assert_allclose(r, 2 * np.ones((4, 2)))
    finally:
        paddle.disable_static()


def test_parallel_executor_shards_feeds():
    import jax
    exe = static.ParallelExecutor()
    v = jax.numpy.ones((16, 4))
    placed = exe._place_feed(v)
    assert len(placed.sharding.device_set) == 8
    # non-divisible batch falls back to replication, not a crash
    odd = jax.numpy.ones((15, 4))
    assert exe._place_feed(odd) is odd
