"""Op-level numeric tests vs numpy golden (SURVEY.md §4; modeled on the
reference's OpTest pattern in python/paddle/fluid/tests/unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def np_t(shape, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return rng.randn(*shape).astype(dtype)


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])

    def test_arange_linspace(self):
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.arange(1, 10, 2).numpy(),
                                   np.arange(1, 10, 2))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)

    def test_eye_diag_tril_triu(self):
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        x = np_t((3, 3))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.tril(t).numpy(), np.tril(x))
        np.testing.assert_allclose(paddle.triu(t).numpy(), np.triu(x))
        np.testing.assert_allclose(paddle.diag(paddle.to_tensor([1., 2.])).numpy(),
                                   np.diag([1., 2.]))

    def test_like_ops(self):
        x = paddle.to_tensor(np_t((2, 3)))
        assert paddle.zeros_like(x).numpy().sum() == 0
        assert paddle.ones_like(x).numpy().sum() == 6
        np.testing.assert_allclose(paddle.full_like(x, 3.0).numpy(),
                                   np.full((2, 3), 3.0))

    def test_meshgrid(self):
        a, b = paddle.meshgrid(paddle.arange(3), paddle.arange(4))
        assert a.shape == [3, 4] and b.shape == [3, 4]


class TestMath:
    def test_binary_elementwise(self):
        x, y = np_t((3, 4)), np_t((3, 4), 1)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        np.testing.assert_allclose((tx + ty).numpy(), x + y, rtol=1e-6)
        np.testing.assert_allclose((tx - ty).numpy(), x - y, rtol=1e-6)
        np.testing.assert_allclose((tx * ty).numpy(), x * y, rtol=1e-6)
        np.testing.assert_allclose((tx / ty).numpy(), x / y, rtol=1e-5)
        np.testing.assert_allclose(paddle.maximum(tx, ty).numpy(),
                                   np.maximum(x, y))
        np.testing.assert_allclose(paddle.pow(tx, 2).numpy(), x ** 2,
                                   rtol=1e-5)

    def test_unary(self):
        x = np.abs(np_t((3, 4))) + 0.5
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.sqrt(t).numpy(), np.sqrt(x),
                                   rtol=1e-6)
        np.testing.assert_allclose(paddle.exp(t).numpy(), np.exp(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.log(t).numpy(), np.log(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.tanh(t).numpy(), np.tanh(x),
                                   rtol=1e-6)
        np.testing.assert_allclose(paddle.rsqrt(t).numpy(), 1 / np.sqrt(x),
                                   rtol=1e-5)

    def test_reductions(self):
        x = np_t((3, 4, 5))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.sum(t).numpy(), x.sum(), rtol=1e-5)
        np.testing.assert_allclose(paddle.sum(t, axis=1).numpy(), x.sum(1),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            paddle.mean(t, axis=[0, 2], keepdim=True).numpy(),
            x.mean((0, 2), keepdims=True), rtol=1e-5)
        np.testing.assert_allclose(paddle.max(t, axis=0).numpy(), x.max(0))
        np.testing.assert_allclose(paddle.prod(t, axis=2).numpy(), x.prod(2),
                                   rtol=1e-4)
        np.testing.assert_allclose(paddle.logsumexp(t).numpy(),
                                   np.log(np.exp(x).sum()), rtol=1e-5)

    def test_matmul(self):
        a, b = np_t((3, 4)), np_t((4, 5))
        np.testing.assert_allclose(
            paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.T),
                          transpose_y=True).numpy(), a @ b, rtol=1e-5)

    def test_cumsum_clip(self):
        x = np_t((3, 4))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.cumsum(t, axis=1).numpy(),
                                   np.cumsum(x, 1), rtol=1e-5)
        np.testing.assert_allclose(paddle.clip(t, -0.5, 0.5).numpy(),
                                   np.clip(x, -0.5, 0.5))

    def test_divide_int(self):
        a = paddle.to_tensor([7, 8], dtype="int32")
        b = paddle.to_tensor([2, 3], dtype="int32")
        np.testing.assert_allclose((a / b).numpy(), [3, 2])


class TestManipulation:
    def test_reshape_transpose(self):
        x = np_t((2, 3, 4))
        t = paddle.to_tensor(x)
        assert paddle.reshape(t, [6, 4]).shape == [6, 4]
        assert paddle.reshape(t, [-1]).shape == [24]
        np.testing.assert_allclose(
            paddle.transpose(t, [2, 0, 1]).numpy(), x.transpose(2, 0, 1))

    def test_concat_split_stack(self):
        x, y = np_t((2, 3)), np_t((2, 3), 1)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        np.testing.assert_allclose(paddle.concat([tx, ty], 0).numpy(),
                                   np.concatenate([x, y], 0))
        parts = paddle.split(paddle.to_tensor(np_t((6, 2))), 3, axis=0)
        assert len(parts) == 3 and parts[0].shape == [2, 2]
        parts = paddle.split(paddle.to_tensor(np_t((6, 2))), [1, 2, 3], axis=0)
        assert [p.shape[0] for p in parts] == [1, 2, 3]
        np.testing.assert_allclose(paddle.stack([tx, ty], 1).numpy(),
                                   np.stack([x, y], 1))

    def test_squeeze_unsqueeze_flatten(self):
        x = np_t((2, 1, 3))
        t = paddle.to_tensor(x)
        assert paddle.squeeze(t, 1).shape == [2, 3]
        assert paddle.unsqueeze(t, [0, 3]).shape == [1, 2, 1, 1, 3]
        assert paddle.flatten(t).shape == [6]
        assert paddle.flatten(paddle.to_tensor(np_t((2, 3, 4))), 1, 2).shape \
            == [2, 12]

    def test_gather_scatter(self):
        x = np_t((5, 3))
        idx = np.array([0, 2, 4])
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(
            paddle.gather(t, paddle.to_tensor(idx)).numpy(), x[idx])
        upd = np_t((3, 3), 2)
        out = paddle.scatter(t, paddle.to_tensor(idx), paddle.to_tensor(upd))
        ref = x.copy()
        ref[idx] = upd
        np.testing.assert_allclose(out.numpy(), ref)

    def test_gather_nd(self):
        x = np_t((3, 4, 5))
        idx = np.array([[0, 1], [2, 3]])
        out = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[[0, 2], [1, 3]])

    def test_tile_expand_flip_roll(self):
        x = np_t((2, 3))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.tile(t, [2, 1]).numpy(),
                                   np.tile(x, (2, 1)))
        assert paddle.expand(paddle.to_tensor(np_t((1, 3))), [4, 3]).shape \
            == [4, 3]
        np.testing.assert_allclose(paddle.flip(t, [0]).numpy(), x[::-1])
        np.testing.assert_allclose(paddle.roll(t, 1, 0).numpy(),
                                   np.roll(x, 1, 0))

    def test_unique_masked_select(self):
        x = np.array([3, 1, 2, 3, 1])
        out = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), [1, 2, 3])
        m = np.array([True, False, True, False, False])
        np.testing.assert_allclose(
            paddle.masked_select(paddle.to_tensor(x),
                                 paddle.to_tensor(m)).numpy(), x[m])

    def test_getitem_setitem(self):
        x = np_t((4, 5))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t[1:3, ::2].numpy(), x[1:3, ::2])
        t[0, 0] = 99.0
        assert t.numpy()[0, 0] == 99.0

    def test_shard_index(self):
        x = paddle.to_tensor(np.array([1, 5, 9]))
        out = paddle.shard_index(x, 10, 2, 0)
        # shard size 5: ids 1->1 (shard0), 5->-1, 9->-1
        np.testing.assert_allclose(out.numpy(), [1, -1, -1])


class TestLogicSearch:
    def test_compare(self):
        x, y = np_t((3,)), np_t((3,), 1)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        np.testing.assert_array_equal((tx > ty).numpy(), x > y)
        np.testing.assert_array_equal(paddle.equal_all(tx, tx).numpy(), True)
        assert paddle.allclose(tx, tx).numpy()

    def test_logical(self):
        a = paddle.to_tensor([True, False])
        b = paddle.to_tensor([True, True])
        np.testing.assert_array_equal(paddle.logical_and(a, b).numpy(),
                                      [True, False])
        np.testing.assert_array_equal(paddle.logical_not(a).numpy(),
                                      [False, True])

    def test_argmax_sort_topk(self):
        x = np_t((4, 5))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.argmax(t, axis=1).numpy(),
                                   x.argmax(1))
        np.testing.assert_allclose(paddle.sort(t, axis=1).numpy(),
                                   np.sort(x, 1))
        vals, idx = paddle.topk(t, 2, axis=1)
        np.testing.assert_allclose(vals.numpy(), -np.sort(-x, 1)[:, :2],
                                   rtol=1e-6)

    def test_where_nonzero(self):
        x = np.array([1.0, -1.0, 2.0])
        out = paddle.where(paddle.to_tensor(x > 0), paddle.to_tensor(x),
                           paddle.to_tensor(np.zeros(3, np.float32)))
        np.testing.assert_allclose(out.numpy(), [1, 0, 2])
        nz = paddle.nonzero(paddle.to_tensor(x > 0))
        np.testing.assert_allclose(nz.numpy(), [[0], [2]])


class TestLinalgStat:
    def test_norm_dist(self):
        x = np_t((3, 4))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.norm(t).numpy(),
                                   np.linalg.norm(x), rtol=1e-5)
        y = np_t((3, 4), 1)
        np.testing.assert_allclose(
            paddle.dist(t, paddle.to_tensor(y)).numpy(),
            np.linalg.norm((x - y).ravel()), rtol=1e-5)

    def test_std_var_median(self):
        x = np_t((100,))
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.std(t).numpy(), x.std(ddof=1),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.var(t).numpy(), x.var(ddof=1),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.median(t).numpy(), np.median(x),
                                   rtol=1e-5)

    def test_cholesky_inv_det(self):
        a = np_t((3, 3))
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        t = paddle.to_tensor(spd)
        L = paddle.cholesky(t).numpy()
        np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(paddle.inv(t).numpy(),
                                   np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(paddle.det(t).numpy(),
                                   np.linalg.det(spd), rtol=1e-4)

    def test_bmm_histogram(self):
        a, b = np_t((2, 3, 4)), np_t((2, 4, 5))
        np.testing.assert_allclose(
            paddle.bmm(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            a @ b, rtol=1e-5)


class TestRandom:
    def test_shapes_and_determinism(self):
        paddle.seed(7)
        a = paddle.rand([3, 4])
        paddle.seed(7)
        b = paddle.rand([3, 4])
        np.testing.assert_allclose(a.numpy(), b.numpy())
        assert paddle.randn([2, 2]).shape == [2, 2]
        r = paddle.randint(0, 10, [100]).numpy()
        assert r.min() >= 0 and r.max() < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))

    def test_bernoulli_multinomial(self):
        p = paddle.full([1000], 0.3)
        s = paddle.bernoulli(p).numpy()
        assert 0.15 < s.mean() < 0.45
        probs = paddle.to_tensor([0.1, 0.2, 0.7])
        idx = paddle.multinomial(probs, 2).numpy()
        assert len(set(idx.tolist())) == 2  # without replacement
