"""Test harness config.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §4).  The container's
sitecustomize initializes the axon TPU backend at interpreter startup, which
can't be undone in-process — so on first entry we re-exec pytest with a clean
environment (JAX_PLATFORMS=cpu, 8 forced host devices, sitecustomize dropped
from PYTHONPATH).  The re-exec happens in pytest_configure after stopping
global capture so the child writes to the real stdout.
"""
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight model-level tests (full pretrain steps, "
        "pallas interpret mode) excluded from the tier-1 budget")
    if os.environ.get("PADDLE_TPU_TEST_MODE") == "1":
        return
    cap = config.pluginmanager.getplugin("capturemanager")
    if cap is not None:
        try:
            cap.stop_global_capturing()
        except Exception:
            pass
    env = dict(os.environ)
    env["PADDLE_TPU_TEST_MODE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = _REPO_ROOT
    os.chdir(_REPO_ROOT)
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


if os.environ.get("PADDLE_TPU_TEST_MODE") == "1":
    import numpy as np
    import pytest

    @pytest.fixture(autouse=True)
    def _seed():
        import paddle_tpu as paddle
        paddle.seed(1234)
        np.random.seed(1234)
        yield
