"""Pipeline-stage serving + composed parallelism e2e (ISSUE 20).

The engines here compose parallelism axes past what ISSUE 15 shipped:
a ('pp','tp') mesh whose stage rows run the 1F1B microbatch loop from
distributed/auto/pipeline.py inside ONE donated decode executable
(models/gpt_pp.py), and the tp x int8 pairing the old tp=1-only quant
guard refused.  The contract is the serving invariants under
composition: token-exact greedy parity with the single-device
reference through churn and preemption, decode_compiles == 1 with
zero steady-state XLA compiles, and deterministic per-stage-per-shard
page bytes.

Everything in this module is ``slow``: tier-1 keeps pp covered through
the compile-free knob/key/topology tests in test_tp_serving.py and
tools/ppserve_smoke.sh's bench phase; these are the e2e parity runs.
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                      num_heads=2, max_seq_len=64, dtype="float32",
                      use_flash=False, remat=False)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    return params, cfg


def _pp_engine(tiny_model, **kw):
    from paddle_tpu.inference.serving import PagedServingEngine
    params, cfg = tiny_model
    kw.setdefault("tp", 2)
    kw.setdefault("pp", 2)
    # slots % pp == 0: decode runs pp microbatches (real 1F1B overlap)
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("seq_buckets", (8, 16, 32))
    kw.setdefault("batch_buckets", (1, 2))
    kw.setdefault("max_queue", 64)
    return PagedServingEngine((params, cfg), **kw)


def _reference(tiny_model, prompt, n):
    import jax.numpy as jnp
    from paddle_tpu.models import gpt as G
    params, cfg = tiny_model
    out = G.generate(params, cfg, jnp.asarray(prompt, jnp.int32)[None], n)
    return list(np.asarray(out)[0, len(prompt):])


class TestPPServing:
    def test_parity_through_churn(self, tiny_model):
        """The tentpole e2e: a churned mixed-length wave through a 2x2
        pp x tp mesh stays token-exact with the single-device
        reference, compiles the decode step ONCE (one stage-loop
        executable spans all stages), and retraces nothing in steady
        state."""
        from paddle_tpu.observability import metrics as obs
        eng = _pp_engine(tiny_model)
        eng.warmup()
        c0 = obs.counter("compile.count").value
        rng = np.random.RandomState(11)
        reqs = []
        for _ in range(10):                 # > slots: the pool churns
            n = int(rng.randint(3, 30))
            p = rng.randint(1, 256, n).astype(np.int32)
            reqs.append(eng.submit(p, int(rng.randint(4, 10))))
        done = eng.run()
        st = eng.stats()
        assert len(done) == 10
        assert st["decode_compiles"] == 1, st
        assert st["pp"] == 2 and st["tp"] == 2
        assert obs.counter("compile.count").value == c0, \
            "pp steady state retraced"
        for r in reqs:
            assert r.tokens == _reference(tiny_model, r.prompt,
                                          r.max_new_tokens), r.id
        assert st["pages_in_use"] == 0      # fully drained: no leaks

    def test_pp_only_mesh_parity(self, tiny_model):
        """pp without tp (2x1 mesh): the stage loop alone carries the
        engine — psum('tp') collectives degenerate to width-1."""
        eng = _pp_engine(tiny_model, tp=1, slots=2)
        eng.warmup()
        rng = np.random.RandomState(12)
        reqs = [eng.submit(rng.randint(1, 256, int(rng.randint(3, 20)))
                           .astype(np.int32), 6) for _ in range(4)]
        eng.run()
        assert eng.stats()["decode_compiles"] == 1
        for r in reqs:
            assert r.tokens == _reference(tiny_model, r.prompt, 6), r.id

    def test_preemption_parity(self, tiny_model):
        """Page exhaustion preempts and re-admits on the pp engine
        exactly like the flat paged engine: both requests complete
        token-exact, the failure named in the counters."""
        eng = _pp_engine(tiny_model, tp=1, slots=2, page_size=4,
                         num_pages=9, seq_buckets=(16,),
                         batch_buckets=(1,), prefix_cache=False)
        eng.warmup()
        a = eng.submit(np.arange(1, 13, dtype=np.int32), 16)
        b = eng.submit(np.arange(3, 15, dtype=np.int32), 16)
        done = eng.run(max_steps=400)       # bounded: no hang
        st = eng.stats()
        assert len(done) == 2 and a.done and b.done
        assert st["preemptions"] >= 1
        for r in (a, b):
            want = _reference(tiny_model, r.prompt, r.max_new_tokens)
            assert list(np.asarray(r.tokens)) == list(want), r.id

    def test_stage_bytes_deterministic(self, tiny_model):
        """Per-stage-per-shard page bytes are deterministic: symmetric
        across the stage rows (the layer split is even), identical
        across independently built engines, and reported through
        stats()."""
        ea = _pp_engine(tiny_model)
        eb = _pp_engine(tiny_model)
        sa, sb = ea.stage_bytes(), eb.stage_bytes()
        assert len(sa) == len(sb) == 2
        assert sa == sb                      # build-for-build identical
        assert sa[0] == sa[1]                # even split: symmetric rows
        assert sa[0]["params"] > 0 and sa[0]["kv"] > 0
        assert ea.stats()["stage_bytes"] == sa
        # traffic must not change what a stage device pins: the pools
        # are statically allocated, pages only re-index inside them
        ea.warmup()
        ea.submit(np.arange(1, 9, dtype=np.int32), 4)
        ea.run()
        assert ea.stage_bytes() == sa


class TestTPInt8Composition:
    def test_tp_int8_parity(self, tiny_model):
        """The composition the old guard refused, end to end: tp=2 +
        int8 weights (+ int8 KV on the paged engine) matches the tp=1
        int8 engine token for token — sharding must not move the
        quantization noise.  (bench.py's tp phase additionally gates
        the int8 tokens against the fp32 single-device reference under
        the declared logit budget.)"""
        from paddle_tpu.inference.serving import (PagedServingEngine,
                                                  ServingEngine)
        params, cfg = tiny_model
        rng = np.random.RandomState(13)
        trace = [(rng.randint(1, 256, int(rng.randint(3, 20)))
                  .astype(np.int32), int(rng.randint(4, 10)))
                 for _ in range(6)]

        def run(eng):
            reqs = [eng.submit(p, m) for p, m in trace]
            eng.run()
            assert eng.stats()["decode_compiles"] == 1
            return [list(r.tokens) for r in reqs]

        for mk in (lambda tp: ServingEngine(
                       (params, cfg), tp=tp, quant="int8", slots=3,
                       max_len=64, seq_buckets=(8, 16, 32),
                       batch_buckets=(1, 2), max_queue=64),
                   lambda tp: PagedServingEngine(
                       (params, cfg), tp=tp, quant="int8",
                       kv_dtype="int8", slots=3, max_len=64,
                       page_size=8, seq_buckets=(8, 16, 32),
                       batch_buckets=(1, 2), max_queue=64)):
            assert run(mk(2)) == run(mk(1))

    def test_tp_int8_prefix_reuse_attestation(self, tiny_model):
        """ISSUE 20's attestation on the composed engine: a second
        request with the same prompt allocates ZERO new prefix pages —
        per shard, since every page's int8 bytes + scale rows are
        head-sharded over 'tp' and reuse is decided once, host-side,
        for all shards."""
        from paddle_tpu.inference.serving import PagedServingEngine
        params, cfg = tiny_model
        eng = PagedServingEngine(
            (params, cfg), tp=2, quant="int8", kv_dtype="int8",
            slots=3, max_len=64, page_size=4, seq_buckets=(8, 16, 32),
            batch_buckets=(1, 2), max_queue=64)
        eng.warmup()
        prompt = np.arange(1, 11, dtype=np.int32)   # 10 tokens, 3 pages
        r1 = eng.submit(prompt, 4)
        eng.run()
        s1 = eng.stats()
        r2 = eng.submit(prompt, 4)
        eng.run()
        s2 = eng.stats()
        assert s2["prefix_page_hits"] - s1["prefix_page_hits"] == 3
        assert s2["prefix_page_misses"] - s1["prefix_page_misses"] == 0
        assert r1.tokens == r2.tokens
        # the shared pages live on BOTH shards: each device holds the
        # head-axis half of every pooled page + its scale rows
        for arr in eng._cache_operands():
            assert len(arr.addressable_shards) == 2
