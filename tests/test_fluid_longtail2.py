"""Tests for the last fluid.layers coverage wave: filter_by_instag,
generate_proposal_labels, codegen helpers, lod reorder
(ref fluid/layers/nn.py:10126, detection.py:2596,
layer_function_generator.py, control_flow.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid


def test_filter_by_instag():
    ins = np.arange(8, dtype=np.float32).reshape(4, 2)
    tags = np.array([[0, 1], [1, 3], [0, 3], [2, 6]], np.int64)
    out, w = fluid.layers.filter_by_instag(
        paddle.to_tensor(ins), paddle.to_tensor(tags),
        paddle.to_tensor(np.array([1], np.int64)), True)
    o, wv = out.numpy(), w.numpy()
    # rows 0 and 1 carry tag 1 -> kept, compacted to the front
    np.testing.assert_allclose(o[0], ins[0])
    np.testing.assert_allclose(o[1], ins[1])
    np.testing.assert_allclose(o[2:], 0.0)     # out_val_if_empty fill
    np.testing.assert_allclose(wv.reshape(-1), [1, 1, 0, 0])

    # no row matches -> all filled with out_val_if_empty, weights 0
    out2, w2 = fluid.layers.filter_by_instag(
        paddle.to_tensor(ins), paddle.to_tensor(tags),
        paddle.to_tensor(np.array([9], np.int64)), True,
        out_val_if_empty=7)
    np.testing.assert_allclose(out2.numpy(), 7.0)
    np.testing.assert_allclose(w2.numpy(), 0.0)


def test_generate_proposal_labels_dense():
    rois = np.array([[[0, 0, 10, 10], [20, 20, 28, 28], [100, 100, 110, 110],
                      [0, 0, 9, 9]]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [21, 21, 29, 29]]], np.float32)
    gcls = np.array([[3, 5]], np.int32)
    crowd = np.zeros((1, 2), np.int32)
    im_info = np.array([[200, 200, 1.0]], np.float32)
    S = 6
    rois_o, labels, tgts, iw, ow = fluid.layers.generate_proposal_labels(
        paddle.to_tensor(rois), paddle.to_tensor(gcls),
        paddle.to_tensor(crowd), paddle.to_tensor(gt),
        paddle.to_tensor(im_info), batch_size_per_im=S, fg_fraction=0.5,
        fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=6)
    lb = labels.numpy()[0]
    ro = rois_o.numpy()[0]
    # fg rows first: roi0 (IoU 1 with gt0, class 3), roi1 (IoU ~.6 gt1,
    # class 5), roi3 (IoU ~.8 gt0), plus the two appended gts themselves
    assert lb.shape == (S,)
    n_fg = (lb > 0).sum()
    assert n_fg == 3                     # capped at fg_fraction * S
    assert set(lb[:n_fg]).issubset({3, 5})
    # bg rows follow (roi2 has IoU 0 in [0, 0.5))
    assert (lb[n_fg:] == 0).sum() >= 1
    # per-class target layout: weights 1 exactly in the label's 4-slot
    t = iw.numpy()[0]
    for i in range(n_fg):
        c = lb[i]
        assert t[i, 4 * c:4 * c + 4].sum() == 4
        assert t[i].sum() == 4
    # exact-match fg roi encodes ~zero offsets in its class slot
    exact = np.where((ro[:, 2] - ro[:, 0] == 10) & (lb == 3))[0][0]
    bt = tgts.numpy()[0]
    np.testing.assert_allclose(bt[exact, 12:16], 0.0, atol=1e-4)


def test_generate_layer_fn_and_activation_fn():
    relu = fluid.layers.generate_activation_fn("relu")
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    np.testing.assert_allclose(relu(x).numpy(), [0, 2])
    fn = fluid.layers.generate_layer_fn("concat")
    out = fn([x, x], axis=0)
    assert out.shape == [4]
    with pytest.raises(ValueError):
        fluid.layers.generate_layer_fn("definitely_not_an_op")


def test_templatedoc_and_autodoc():
    @fluid.layers.templatedoc()
    def f(x):
        """Computes ${comment} over x. ${another_comment}Done."""
        return x
    assert "${" not in f.__doc__
    assert "Done." in f.__doc__

    @fluid.layers.autodoc(" extra")
    def g(x):
        """doc"""
        return x
    assert g.__doc__.endswith("extra")


def test_reorder_lod_tensor_by_rank():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    lens = np.array([2, 5, 1, 4], np.int64)
    table = fluid.layers.lod_rank_table(None, lengths=paddle.to_tensor(lens))
    np.testing.assert_array_equal(table.numpy(), [1, 3, 0, 2])
    out = fluid.layers.reorder_lod_tensor_by_rank(paddle.to_tensor(x),
                                                  table)
    np.testing.assert_allclose(out.numpy(), x[[1, 3, 0, 2]])
