"""static.nn long-tail builders: crf_decoding vs brute-force Viterbi,
row_conv/nce/data_norm numerics, the extra sequence ops."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn
import paddle_tpu.nn.functional as F


class TestCrfDecoding:
    def _brute(self, emis, trans, length):
        """Enumerate all paths, reference layout: trans[0]=start,
        trans[1]=stop, trans[2:]=[D,D]."""
        D = emis.shape[-1]
        best, best_s = None, -1e30
        for path in itertools.product(range(D), repeat=length):
            s = trans[0, path[0]] + emis[0, path[0]]
            for t in range(1, length):
                s += trans[2 + path[t - 1], path[t]] + emis[t, path[t]]
            s += trans[1, path[-1]]
            if s > best_s:
                best, best_s = path, s
        return list(best)

    def test_vs_bruteforce(self):
        rng = np.random.RandomState(0)
        B, T, D = 3, 5, 4
        emis = rng.randn(B, T, D).astype("float32")
        trans = rng.randn(D + 2, D).astype("float32")
        lens = np.array([5, 3, 1], np.int64)
        out = snn.crf_decoding(paddle.to_tensor(emis),
                               paddle.to_tensor(trans),
                               paddle.to_tensor(lens)).numpy()
        for b in range(B):
            ref = self._brute(emis[b], trans, int(lens[b]))
            np.testing.assert_array_equal(out[b, :lens[b]], ref,
                                          err_msg=f"seq {b}")
            assert (out[b, lens[b]:] == 0).all()

    def test_full_length_default(self):
        rng = np.random.RandomState(1)
        emis = rng.randn(2, 4, 3).astype("float32")
        trans = rng.randn(5, 3).astype("float32")
        out = snn.crf_decoding(paddle.to_tensor(emis),
                               paddle.to_tensor(trans)).numpy()
        for b in range(2):
            ref = self._brute(emis[b], trans, 4)
            np.testing.assert_array_equal(out[b], ref)


class TestRowConvNceDataNorm:
    def test_row_conv(self):
        x = np.arange(12, dtype=np.float32).reshape(1, 4, 3)
        out = snn.row_conv(paddle.to_tensor(x), 2)
        assert out.shape == [1, 4, 3]
        # with weight w: out[t] = sum_j w[j]*x[t+j]; check via the param
        # the builder registered (last created parameter)
        assert np.isfinite(out.numpy()).all()

    def test_row_conv_identity_weight(self):
        # manual: same math with a known weight by calling the inner op
        import jax.numpy as jnp
        x = np.random.RandomState(0).randn(2, 5, 3).astype("float32")
        k = 1
        w = np.random.RandomState(1).randn(k + 1, 3).astype("float32")
        ref = np.zeros_like(x)
        for j in range(k + 1):
            shifted = np.pad(x, ((0, 0), (0, j), (0, 0)))[:, j:j + 5]
            ref += shifted * w[j]
        # reproduce through the public builder by overwriting its param
        out_t = snn.row_conv(paddle.to_tensor(x), k)
        # builder created its own random weight; recompute with ours:
        from paddle_tpu.ops.dispatch import call
        out2 = call(lambda a, b: sum(
            jnp.pad(a, ((0, 0), (0, j), (0, 0)))[:, j:j + 5] * b[j]
            for j in range(k + 1)), paddle.to_tensor(x), paddle.to_tensor(w))
        np.testing.assert_allclose(out2.numpy(), ref, atol=1e-5)

    def test_nce_shape_and_grad(self):
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(6, 8).astype("float32"))
        x.stop_gradient = False
        lbl = paddle.to_tensor(np.random.RandomState(3).randint(0, 50, (6, 1)))
        loss = snn.nce(x, lbl, 50, num_neg_samples=5, seed=7)
        assert loss.shape == [6, 1]
        assert (loss.numpy() > 0).all()
        loss.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()

    def test_data_norm(self):
        x = paddle.to_tensor(
            np.random.RandomState(4).randn(5, 3).astype("float32") * 10)
        out = snn.data_norm(x)
        # default stats: n=1e4, sum=0, sqsum=1e4 -> mean 0, var 1e-4... the
        # normalization is x / sqrt(max(var, eps)); just check finite+shape
        assert out.shape == [5, 3]
        assert np.isfinite(out.numpy()).all()

    def test_conv3d_transpose(self):
        x = paddle.to_tensor(
            np.random.RandomState(5).randn(1, 2, 3, 4, 4).astype("float32"))
        out = snn.conv3d_transpose(x, 3, 2, stride=2)
        assert out.shape[0] == 1 and out.shape[1] == 3
        assert out.shape[2] == 6


class TestSequenceLongtail:
    def test_sequence_reshape(self):
        x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        lens = paddle.to_tensor(np.array([3, 2]))
        out, nl = F.sequence_reshape(x, lens, 6)
        assert out.shape == [2, 2, 6]
        np.testing.assert_array_equal(np.asarray(nl.numpy()), [2, 1])

    def test_sequence_expand_as(self):
        x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        lens = paddle.to_tensor(np.array([3, 1]))
        out = F.sequence_expand_as(x, lens)
        assert out.shape == [2, 3, 2]
        np.testing.assert_allclose(out.numpy()[0, 2], [1, 2])
        np.testing.assert_allclose(out.numpy()[1, 1], [0, 0])  # masked

    def test_sequence_slice(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(2, 6))
        lens = paddle.to_tensor(np.array([6, 4]))
        off = paddle.to_tensor(np.array([1, 0]))
        ln = paddle.to_tensor(np.array([3, 2]))
        out, nl = F.sequence_slice(x, lens, off, ln)
        np.testing.assert_allclose(out.numpy()[0, :3], [1, 2, 3])
        assert (out.numpy()[0, 3:] == 0).all()
        np.testing.assert_allclose(out.numpy()[1, :2], [6, 7])

    def test_sequence_scatter(self):
        x = paddle.to_tensor(np.zeros((2, 5), np.float32))
        idx = paddle.to_tensor(np.array([[0, 2], [4, 4]]))
        upd = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 9.0]], "float32"))
        lens = paddle.to_tensor(np.array([2, 1]))
        out = F.sequence_scatter(x, idx, upd, lens)
        np.testing.assert_allclose(out.numpy()[0], [1, 0, 2, 0, 0])
        np.testing.assert_allclose(out.numpy()[1], [0, 0, 0, 0, 3])

    def test_static_nn_reexports(self):
        assert snn.sequence_pad is F.sequence_pad
        assert snn.py_func is paddle.static.py_func
        assert callable(snn.sparse_embedding)
        assert callable(snn.create_parameter)
