"""PS-era data plumbing + fleet util + initializer long tail
(ref distributed/entry_attr.py, fleet/data_generator/, fleet/dataset/,
fleet/base/util_factory.py, fluid/initializer.py:733,959)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fluid
from paddle_tpu.distributed import fleet


class TestEntries:
    def test_entry_attrs(self):
        import paddle_tpu.distributed as dist
        assert dist.ProbabilityEntry(0.5)._to_attr() == \
            "probability_entry:0.5"
        assert dist.CountFilterEntry(10)._to_attr() == \
            "count_filter_entry:10"
        with pytest.raises(ValueError):
            dist.ProbabilityEntry(1.5)
        with pytest.raises(ValueError):
            dist.CountFilterEntry(-1)


class TestDataGenerator:
    def test_multislot_protocol_golden(self):
        g = fleet.MultiSlotDataGenerator()
        line = g._gen_str([("words", [1926, 8, 17]), ("label", [1])])
        assert line == "3 1926 8 17 1 1\n"
        assert g._proto_info == [("words", "uint64"), ("label", "uint64")]
        # float promotes the slot kind
        g._gen_str([("words", [1.5, 2.0, 3.0]), ("label", [0])])
        assert g._proto_info[0] == ("words", "float")
        with pytest.raises(ValueError):       # field-count mismatch
            g._gen_str([("words", [1])])

    def test_multislot_string_protocol(self):
        g = fleet.MultiSlotStringDataGenerator()
        assert g._gen_str([("w", ["a", "b"]), ("l", ["1"])]) == \
            "2 a b 1 1\n"

    def test_run_from_memory(self, capsys):
        class G(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    for i in range(3):
                        yield [("ids", [i, i + 1]), ("label", [i % 2])]
                return it
        G().run_from_memory()
        out = capsys.readouterr().out.strip().splitlines()
        assert out == ["2 0 1 1 0", "2 1 2 1 1", "2 2 3 1 0"]


def _write_protocol(tmp_path, rows):
    p = os.path.join(str(tmp_path), "part-0")
    with open(p, "w") as f:
        for ids, label in rows:
            f.write(f"{len(ids)} {' '.join(map(str, ids))} 1 {label}\n")
    return p


class TestDatasets:
    def _vars(self):
        paddle.enable_static()
        ids = fluid.layers.data("ds_ids", [-1], dtype="int64")
        lbl = fluid.layers.data("ds_label", [1], dtype="int64")
        return ids, lbl

    def test_in_memory_dataset(self, tmp_path):
        try:
            ids, lbl = self._vars()
            import paddle_tpu.distributed as dist
            ds = dist.InMemoryDataset()
            ds.init(batch_size=2, use_var=[ids, lbl])
            p = _write_protocol(tmp_path,
                                [([1, 2], 0), ([3], 1), ([4, 5, 6], 0)])
            ds.set_filelist([p])
            ds.load_into_memory()
            assert ds.get_memory_data_size() == 3
            ds._seed = 0
            ds.local_shuffle()
            batches = list(ds.iter_batches())
            assert len(batches) == 2            # 2 + 1
            b0 = batches[0]
            assert set(b0) == {"ds_ids", "ds_label"}
            assert b0["ds_ids"].dtype == np.int64
            # padded to batch max
            assert b0["ds_ids"].shape[0] == 2
            ds.release_memory()
            assert ds.get_memory_data_size() == 0
        finally:
            paddle.disable_static()

    def test_queue_dataset_and_pipe_command(self, tmp_path):
        try:
            ids, lbl = self._vars()
            import paddle_tpu.distributed as dist
            raw = os.path.join(str(tmp_path), "raw.txt")
            with open(raw, "w") as f:
                f.write("7 8\n9 10\n")
            ds = dist.QueueDataset()
            # pipe turns "a b" into "2 a b 1 0" protocol rows
            ds.init(batch_size=1, use_var=[ids, lbl],
                    pipe_command=(
                        "awk '{print 2, $1, $2, 1, 0}'"))
            ds.set_filelist([raw])
            batches = list(ds.iter_batches())
            assert len(batches) == 2
            np.testing.assert_array_equal(batches[0]["ds_ids"],
                                          [[7, 8]])
        finally:
            paddle.disable_static()

    def test_train_from_dataset(self, tmp_path):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("tfd_x", [2], dtype="float32")
                y = fluid.layers.data("tfd_y", [1], dtype="float32")
                pred = fluid.layers.fc(x, 1)
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square(pred - y))
                opt = fluid.optimizer.SGD(learning_rate=0.1)
                opt.minimize(loss)

                import paddle_tpu.distributed as dist
                ds = dist.InMemoryDataset()
                ds.init(batch_size=2, use_var=[x, y])
                p = os.path.join(str(tmp_path), "train.txt")
                with open(p, "w") as f:
                    for _ in range(8):
                        f.write("2 1.0 2.0 1 3.0\n")
                ds.set_filelist([p])
                ds.load_into_memory()

                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                w0 = np.asarray(main.all_parameters()[0].numpy()).copy()
                exe.train_from_dataset(main, ds, fetch_list=[loss])
                w1 = np.asarray(main.all_parameters()[0].numpy())
                assert not np.allclose(w0, w1)   # it trained
        finally:
            paddle.disable_static()


    def test_infer_from_dataset_never_trains(self, tmp_path):
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("ifd_x", [2], dtype="float32")
                y = fluid.layers.data("ifd_y", [1], dtype="float32")
                loss = fluid.layers.reduce_mean(fluid.layers.square(
                    fluid.layers.fc(x, 1) - y))
                fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)

                import paddle_tpu.distributed as dist
                ds = dist.InMemoryDataset()
                ds.init(batch_size=2, use_var=[x, y])
                p = os.path.join(str(tmp_path), "eval.txt")
                with open(p, "w") as f:
                    f.write("2 1.0 2.0 1 3.0\n" * 4)
                ds.set_filelist([p])
                ds.load_into_memory()

                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                w0 = np.asarray(main.all_parameters()[0].numpy()).copy()
                exe.infer_from_dataset(main, ds, fetch_list=[loss])
                w1 = np.asarray(main.all_parameters()[0].numpy())
                np.testing.assert_array_equal(w0, w1)   # no updates
                assert main.train_spec is not None      # spec restored
        finally:
            paddle.disable_static()

    def test_trailing_tokens_rejected(self, tmp_path):
        try:
            ids, lbl = self._vars()
            import paddle_tpu.distributed as dist
            ds = dist.InMemoryDataset()
            ds.init(batch_size=1, use_var=[ids, lbl])
            p = os.path.join(str(tmp_path), "bad.txt")
            with open(p, "w") as f:
                f.write("1 5 1 0 99 99\n")       # stray trailing tokens
            ds.set_filelist([p])
            with pytest.raises(ValueError, match="trailing"):
                ds.load_into_memory()
        finally:
            paddle.disable_static()


class TestFleetUtil:
    def test_get_file_shard_and_topology(self):
        u = fleet.UtilBase()
        files = [f"f{i}" for i in range(5)]
        assert u.get_file_shard(files) == files   # world of one
        assert u.all_reduce(np.array([2.0]), "sum") == 2.0
        assert u.all_gather(3) == [3]

        topo = fleet.CommunicateTopology(["data", "pipe", "model"],
                                         [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=0, pipe=0, model=1) == 1
        assert topo.get_rank(data=1, pipe=0, model=0) == 4
        assert topo.get_coord(5) == topo.coordinate(1, 0, 1)
        assert topo.get_axis_list("model", 0) == [0, 2, 4, 6]
        assert [0, 1] in topo.get_comm_list("model")
        assert fleet.Role.WORKER == 1 and fleet.Role.SERVER == 2

    def test_fleet_util_singleton(self):
        assert isinstance(fleet.util, fleet.UtilBase)


class TestInitializerLongTail:
    def test_bilinear_golden(self):
        init = paddle.nn.initializer.Bilinear()
        w = np.asarray(init([1, 1, 4, 4], "float32"))
        row = np.array([0.25, 0.75, 0.75, 0.25], np.float32)
        np.testing.assert_allclose(w[0, 0], np.outer(row, row), rtol=1e-6)
        with pytest.raises(ValueError):
            init([1, 1, 3, 4], "float32")

    def test_bilinear_conv_transpose_upsamples(self):
        # factor-2 upsampling of a constant map stays constant (interior)
        init = paddle.nn.initializer.Bilinear()
        conv = paddle.nn.Conv2DTranspose(
            1, 1, 4, stride=2, padding=1,
            weight_attr=paddle.ParamAttr(initializer=init),
            bias_attr=False)
        x = paddle.to_tensor(np.ones((1, 1, 8, 8), "float32"))
        y = np.asarray(conv(x).numpy())
        assert y.shape == (1, 1, 16, 16)
        np.testing.assert_allclose(y[0, 0, 4:12, 4:12], 1.0, rtol=1e-5)

    def test_set_global_initializer(self):
        from paddle_tpu.nn.initializer import set_global_initializer
        try:
            set_global_initializer(paddle.nn.initializer.Constant(3.0),
                                   paddle.nn.initializer.Constant(-1.0))
            lin = paddle.nn.Linear(2, 2)
            np.testing.assert_allclose(np.asarray(lin.weight.numpy()), 3.0)
            np.testing.assert_allclose(np.asarray(lin.bias.numpy()), -1.0)
            # explicit ParamAttr initializer still wins
            lin2 = paddle.nn.Linear(
                2, 2, weight_attr=paddle.ParamAttr(
                    initializer=paddle.nn.initializer.Constant(7.0)))
            np.testing.assert_allclose(np.asarray(lin2.weight.numpy()),
                                       7.0)
            with pytest.raises(TypeError):
                set_global_initializer("not an initializer")
        finally:
            set_global_initializer(None, None)
        lin3 = paddle.nn.Linear(2, 2)     # defaults restored
        assert np.asarray(lin3.weight.numpy()).std() > 0
