"""Launcher process management, spawn fan-out, jax.distributed bootstrap,
and the gradient-merge meta-optimizer (VERDICT r2 weak items 8-9)."""
import os
import socket
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.launch import launch_procs
from paddle_tpu.optimizer import GradientMergeOptimizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env_base():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_launch_procs_runs_all_ranks(tmp_path):
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent("""
        import os
        rank = os.environ["PADDLE_TRAINER_ID"]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        with open(os.path.join(%r, f"out_{rank}.txt"), "w") as f:
            f.write(f"{rank}/{n}")
    """ % str(tmp_path)))
    rc = launch_procs([str(script)], nprocs=3, master=None,
                      env_base=_env_base())
    assert rc == 0
    for r in range(3):
        assert (tmp_path / f"out_{r}.txt").read_text() == f"{r}/3"


def test_launch_procs_propagates_failure(tmp_path):
    script = tmp_path / "f.py"
    script.write_text(
        "import os, sys\n"
        "sys.exit(7 if os.environ['PADDLE_TRAINER_ID'] == '1' else 0)\n")
    rc = launch_procs([str(script)], nprocs=2, master=None,
                      env_base=_env_base())
    assert rc == 7


def test_launch_jax_distributed_bootstrap(tmp_path):
    """Two real processes connect through jax.distributed.initialize —
    the multi-host path the round-2 verdict called untested."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "dist.py"
    script.write_text(textwrap.dedent(f"""
        import os
        import paddle_tpu.distributed as dist
        import jax
        dist.init_parallel_env()
        assert jax.process_count() == 2, jax.process_count()
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        assert jax.process_index() == rank
        with open(os.path.join({str(tmp_path)!r}, f"ok_{{rank}}"), "w"):
            pass
    """))
    rc = launch_procs([str(script)], nprocs=2,
                      master=f"127.0.0.1:{port}", env_base=_env_base())
    assert rc == 0
    assert (tmp_path / "ok_0").exists() and (tmp_path / "ok_1").exists()


def test_supervisor_restarts_failed_worker(tmp_path):
    """A worker killed mid-run is relaunched within the restart budget:
    the WHOLE group restarts with PADDLE_RESTART_COUNT bumped, and the
    run converges to rc 0 once the fault stops firing."""
    from paddle_tpu.distributed.launch import supervise
    script = tmp_path / "flaky.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        restart = os.environ["PADDLE_RESTART_COUNT"]
        open(os.path.join({str(tmp_path)!r},
                          f"ran_{{rank}}_{{restart}}"), "w").close()
        if rank == "1" and restart == "0":
            sys.exit(9)      # die once, first incarnation only
    """))
    summary = supervise([str(script)], nprocs=2, env_base=_env_base(),
                        max_restarts=2, backoff=0.05)
    assert summary["rc"] == 0
    assert summary["restarts_used"] == 1
    assert len(summary["incidents"]) == 1
    inc = summary["incidents"][0]
    assert inc["rank"] == 1 and inc["exit_code"] == 9 \
        and inc["incarnation"] == 0
    # every rank ran in BOTH incarnations (group-wide relaunch)
    for rank in (0, 1):
        for restart in (0, 1):
            assert (tmp_path / f"ran_{rank}_{restart}").exists()


def test_supervisor_budget_exhaustion_propagates_exit_code(tmp_path):
    """Restart budget spent: the original failing exit code is the
    launcher's, and every incident is on the record."""
    from paddle_tpu.distributed.launch import supervise, launch_procs
    script = tmp_path / "hopeless.py"
    script.write_text("import sys; sys.exit(5)\n")
    summary = supervise([str(script)], nprocs=2, env_base=_env_base(),
                        max_restarts=1, backoff=0.05)
    assert summary["rc"] == 5
    assert summary["restarts_used"] == 1
    assert len(summary["incidents"]) == 2     # original + failed retry
    assert summary["failed_rank"] is not None
    # the back-compat wrapper propagates the same code
    assert launch_procs([str(script)], nprocs=1, master=None,
                        env_base=_env_base()) == 5


def test_supervisor_sigterms_survivors_exactly_once(tmp_path):
    """On an incident the surviving workers get ONE SIGTERM each (then a
    grace period), never a second."""
    from paddle_tpu.distributed.launch import supervise
    marker = tmp_path / "sigterms.txt"
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(f"""
        import os, signal, sys, time
        rank = os.environ["PADDLE_TRAINER_ID"]
        if rank == "1":
            time.sleep(0.3)
            sys.exit(3)          # the failing worker
        def onterm(sig, frame):
            with open({str(marker)!r}, "a") as f:
                f.write(f"TERM rank={{rank}}\\n")
            sys.exit(0)
        signal.signal(signal.SIGTERM, onterm)
        time.sleep(60)           # survivor: waits to be torn down
    """))
    summary = supervise([str(script)], nprocs=2, env_base=_env_base(),
                        max_restarts=0)
    assert summary["rc"] == 3
    lines = marker.read_text().splitlines()
    assert lines == ["TERM rank=0"]     # exactly one signal, rank 0 only


def test_supervisor_log_dir_and_exit_summary(tmp_path):
    """--log_dir really writes workerN.log (stdout+stderr) and the exit
    summary names the failing worker's log."""
    from paddle_tpu.distributed.launch import supervise
    script = tmp_path / "noisy.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        print(f"hello stdout from rank {rank}")
        print(f"hello stderr from rank {rank}", file=sys.stderr)
        sys.exit(11 if rank == "1" else 0)
    """))
    log_dir = tmp_path / "logs"
    summary = supervise([str(script)], nprocs=2, env_base=_env_base(),
                        log_dir=str(log_dir))
    assert summary["rc"] == 11
    assert summary["failed_rank"] == 1
    assert summary["failed_log"].endswith("worker1.log")
    for rank in (0, 1):
        text = (log_dir / f"worker{rank}.log").read_text()
        assert f"hello stdout from rank {rank}" in text
        assert f"hello stderr from rank {rank}" in text   # merged stream


def test_spawn_multiprocess(tmp_path):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import spawn_helper
        paddle.distributed.spawn(spawn_helper.write_rank,
                                 args=(str(tmp_path),), nprocs=2)
    finally:
        sys.path.pop(0)
    assert (tmp_path / "rank_0.txt").exists()
    assert (tmp_path / "rank_1.txt").exists()


# ------------------------------------------------------ gradient merge ----

def test_gradient_merge_matches_big_batch():
    rng = np.random.RandomState(0)
    w0 = rng.randn(4, 3).astype(np.float32)
    xs = [rng.randn(8, 4).astype(np.float32) for _ in range(4)]
    ys = [rng.randn(8, 3).astype(np.float32) for _ in range(4)]

    def make():
        lin = paddle.nn.Linear(4, 3)
        lin.weight.set_value(paddle.to_tensor(w0))
        lin.bias.set_value(paddle.to_tensor(np.zeros(3, np.float32)))
        return lin

    # merged: 4 micro-steps of batch 8
    lin_a = make()
    opt_a = GradientMergeOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin_a.parameters()), k_steps=4)
    for x, y in zip(xs, ys):
        loss = ((lin_a(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2
                ).mean()
        loss.backward()
        w_before = lin_a.weight.numpy().copy()
        opt_a.step()
        opt_a.clear_grad()
    # big batch: one step of batch 32 (mean over 4 micro-means = same
    # gradient because micro batches are equal sized)
    lin_b = make()
    opt_b = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=lin_b.parameters())
    xb = np.concatenate(xs)
    yb = np.concatenate(ys)
    loss = ((lin_b(paddle.to_tensor(xb)) - paddle.to_tensor(yb)) ** 2
            ).mean()
    loss.backward()
    opt_b.step()
    np.testing.assert_allclose(lin_a.weight.numpy(), lin_b.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_gradient_merge_no_update_midway():
    lin = paddle.nn.Linear(2, 2)
    opt = GradientMergeOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters()), k_steps=3)
    w0 = lin.weight.numpy().copy()
    for i in range(2):
        loss = (lin(paddle.to_tensor(np.ones((4, 2), np.float32))) ** 2
                ).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_array_equal(lin.weight.numpy(), w0)  # not yet
    loss = (lin(paddle.to_tensor(np.ones((4, 2), np.float32))) ** 2).mean()
    loss.backward()
    opt.step()
    assert not np.array_equal(lin.weight.numpy(), w0)      # applied


def test_fleet_strategy_gradient_merge_wraps():
    from paddle_tpu.distributed import fleet
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 4, "avg": True}
    fleet.init(is_collective=True, strategy=strat)
    lin = paddle.nn.Linear(2, 2)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters()))
    assert isinstance(opt, GradientMergeOptimizer)
    assert opt._k == 4


class TestDistributedUtils:
    def test_cluster_descriptors(self):
        from paddle_tpu.distributed import utils as U
        cluster, pod = U.get_cluster(
            ["10.0.0.1", "10.0.0.2"], "10.0.0.2",
            ["10.0.0.1:6170", "10.0.0.1:6171",
             "10.0.0.2:6170", "10.0.0.2:6171"], [0, 1])
        assert cluster.trainers_nranks() == 4
        assert pod.rank == 1
        assert pod.trainers[0].rank == 2
        assert cluster.trainers_endpoints()[3] == "10.0.0.2:6171"

    def test_free_ports_and_host(self):
        from paddle_tpu.distributed import utils as U
        ports = U.find_free_ports(3)
        assert len(ports) == 3 and all(1024 < p < 65536 for p in ports)
        assert U.get_host_name_ip() is None or \
            len(U.get_host_name_ip()) == 2

    def test_start_watch_terminate_local(self, tmp_path):
        import sys
        from paddle_tpu.distributed import utils as U
        script = tmp_path / "w.py"
        script.write_text("import os, sys\n"
                          "print('rank', os.environ['PADDLE_TRAINER_ID'])\n")
        cluster, pod = U.get_cluster(["127.0.0.1"], "127.0.0.1",
                                     ["127.0.0.1:6200", "127.0.0.1:6201"],
                                     [0, 1])
        procs = U.start_local_trainers(cluster, pod, str(script), [],
                                       log_dir=str(tmp_path))
        import time
        deadline = time.time() + 30
        while procs and time.time() < deadline:
            procs = U.watch_local_trainers(procs, 2)
            time.sleep(0.2)
        assert not procs
        logs = sorted(str(p) for p in tmp_path.glob("workerlog.*"))
        assert len(logs) == 2
        assert "rank 0" in open(logs[0]).read()

    def test_failed_trainer_raises(self, tmp_path):
        from paddle_tpu.distributed import utils as U
        import pytest, time
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(3)\n")
        cluster, pod = U.get_cluster(["127.0.0.1"], "127.0.0.1",
                                     ["127.0.0.1:6300"], [0])
        procs = U.start_local_trainers(cluster, pod, str(script), [],
                                       log_dir=str(tmp_path))
        with pytest.raises(RuntimeError, match="exited with code 3"):
            deadline = time.time() + 30
            while time.time() < deadline:
                procs = U.watch_local_trainers(procs, 1)
                if not procs:
                    break
                time.sleep(0.2)
        U.terminate_local_procs(procs)


def test_launch_eager_cross_process_collectives(tmp_path):
    """Host-level collectives in a REAL 2-process jax.distributed world:
    outside any mapped axis they must aggregate across processes (the
    reference's gloo control-plane), not return the local value — the
    LocalSGD fleet wrapper and fleet.util metrics depend on it."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "coll.py"
    script.write_text(textwrap.dedent(f"""
        import os
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective
        from paddle_tpu.parallel.localsgd import LocalSGDOptimizer

        dist.init_parallel_env()
        rank = int(os.environ["PADDLE_TRAINER_ID"])

        # all_reduce SUM over distinct per-rank values
        t = paddle.to_tensor(np.full((3,), float(rank + 1), np.float32))
        collective.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.full((3,), 3.0))

        # all_gather returns every rank's row, in rank order
        out = []
        collective.all_gather(out, paddle.to_tensor(
            np.asarray([float(rank)], np.float32)))
        assert len(out) == 2, len(out)
        np.testing.assert_allclose(
            np.concatenate([o.numpy() for o in out]), [0.0, 1.0])

        # broadcast adopts src's value everywhere
        b = paddle.to_tensor(np.asarray([10.0 * (rank + 1)], np.float32))
        collective.broadcast(b, src=1)
        np.testing.assert_allclose(b.numpy(), [20.0])

        # object gather with different payload sizes per rank
        objs = []
        collective.all_gather_object(objs, {{"rank": rank,
                                             "pad": "x" * (rank * 17)}})
        assert [o["rank"] for o in objs] == [0, 1]

        collective.barrier()

        # fleet.util metric aggregation
        from paddle_tpu.distributed import fleet
        fleet.init(is_collective=True)
        total = fleet.util.all_reduce(np.asarray([rank + 1.0]), mode="sum")
        np.testing.assert_allclose(total, [3.0])

        # LocalSGD: per-rank params diverge, one synced step averages them
        lin = paddle.nn.Linear(2, 2)
        w = np.full((2, 2), float(rank), np.float32)
        lin.weight.set_value(paddle.to_tensor(w))
        lin.bias.set_value(paddle.to_tensor(np.zeros(2, np.float32)))
        opt = LocalSGDOptimizer(
            paddle.optimizer.SGD(learning_rate=0.0,
                                 parameters=lin.parameters()),
            k_steps=1, begin_step=1)
        loss = (lin(paddle.to_tensor(np.ones((1, 2), np.float32))) ** 2
                ).mean()
        loss.backward()
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(),
                                   np.full((2, 2), 0.5), atol=1e-6)
        assert not lin.weight.stop_gradient   # sync must not freeze params

        # fleet.utils.fused_allreduce_gradients averages grads cross-rank
        from paddle_tpu.distributed.fleet import utils as fu
        lin2 = paddle.nn.Linear(2, 2)
        lin2.weight.set_value(paddle.to_tensor(
            np.eye(2, dtype=np.float32)))
        lin2.bias.set_value(paddle.to_tensor(np.zeros(2, np.float32)))
        scale = float(rank + 1)     # rank-dependent loss scale
        loss2 = (lin2(paddle.to_tensor(
            np.ones((1, 2), np.float32))) ** 2).sum() * scale
        loss2.backward()
        g_own = lin2.weight.grad.numpy().copy()
        fu.fused_allreduce_gradients(list(lin2.parameters()))
        np.testing.assert_allclose(lin2.weight.grad.numpy(),
                                   g_own / scale * 1.5, atol=1e-5)

        # DataParallel auto-syncs grads across processes during backward
        lin3 = paddle.nn.Linear(2, 2)
        lin3.weight.set_value(paddle.to_tensor(
            np.eye(2, dtype=np.float32)))
        lin3.bias.set_value(paddle.to_tensor(np.zeros(2, np.float32)))
        dp = paddle.DataParallel(lin3)
        loss3 = (dp(paddle.to_tensor(
            np.ones((1, 2), np.float32))) ** 2).sum() * scale
        loss3.backward()
        g3 = lin3.weight.grad.numpy()
        np.testing.assert_allclose(g3, g3[...] * 0 + g_own / scale * 1.5,
                                   atol=1e-5)   # averaged, rank-identical
        with dp.no_sync():
            loss4 = (dp(paddle.to_tensor(
                np.ones((1, 2), np.float32))) ** 2).sum() * scale
            lin3.clear_gradients()
            loss4.backward()
        g4 = lin3.weight.grad.numpy()
        np.testing.assert_allclose(g4, g_own, atol=1e-5)  # local only

        with open(os.path.join({str(tmp_path)!r}, f"cok_{{rank}}"), "w"):
            pass
    """))
    rc = launch_procs([str(script)], nprocs=2,
                      master=f"127.0.0.1:{port}", env_base=_env_base())
    assert rc == 0
    assert (tmp_path / "cok_0").exists() and (tmp_path / "cok_1").exists()
