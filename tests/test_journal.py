"""Write-ahead journal unit tests (ISSUE 18): wire-format round trip,
batched fsync, checkpoint compaction bounding the on-disk footprint,
torn-tail and corrupt-record tolerance (fixture logs AND the injected
faults), the resume-time deadline math, and the replayed-state
semantics reconciliation depends on (a lost admit with a surviving
completion is a recovered result, not a lost request).

All stdlib-speed — no jax, no subprocesses.
"""
import os

import pytest

from paddle_tpu.inference import journal as J
from paddle_tpu.observability import metrics
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


def _stats():
    return dict(J.journal_stats())


def _write_segment(dirpath, records, seq=0):
    """A fixture segment written byte-for-byte like the writer does."""
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, "seg-%08d.log" % seq)
    with open(path, "wb") as f:
        for rec in records:
            f.write(J.encode_record(rec))
    return path


ADMIT = {"t": "admit", "id": "a", "prompt": [1, 2, 3],
         "max_new_tokens": 4, "eos_token": None, "deadline_s": None,
         "priority": "interactive", "phase": None, "admit_wall": 100.0}


# --------------------------------------------------------- round trip ----

class TestRoundTrip:
    def test_writer_replay_round_trip(self, tmp_path):
        d = str(tmp_path / "wal")
        w = J.JournalWriter(d, sync_ms=0)
        w.append({"t": "meta", "model_spec": "{}", "role_plan": ["u"]})
        w.append(dict(ADMIT))
        w.append({"t": "dispatch", "id": "a", "rep": 1})
        w.append(dict(ADMIT, id="b"))
        w.append({"t": "done", "id": "b", "tokens": [7, 8],
                  "finish_reason": "length"})
        w.close()
        st = J.replay(d)
        assert st.records == 5
        assert st.meta["role_plan"] == ["u"]
        assert st.requests["a"]["status"] == "pending"
        assert st.requests["a"]["replica"] == 1
        assert st.requests["b"]["status"] == "done"
        assert st.requests["b"]["tokens"] == [7, 8]
        assert [v["id"] for v in st.live_requests()] == ["a"]
        assert st.lost_ids() == []

    def test_replay_missing_dir_is_empty(self, tmp_path):
        st = J.replay(str(tmp_path / "nope"))
        assert st.records == 0 and st.requests == {}

    def test_payload_hash_canonical(self):
        a = J.payload_hash({"arrays": [{"shape": [1], "data": "xx"}]})
        b = J.payload_hash({"arrays": [{"data": "xx", "shape": [1]}]})
        assert a == b and len(a) == 32
        assert a != J.payload_hash({"arrays": []})


# --------------------------------------------------------- durability ----

class TestDurability:
    def test_fsync_is_batched(self, tmp_path):
        w = J.JournalWriter(str(tmp_path / "wal"), sync_ms=60_000)
        before = _stats()["syncs"]
        for i in range(5):
            w.append(dict(ADMIT, id=f"r{i}"))
        assert w.maybe_sync() is False          # inside the batch window
        assert _stats()["syncs"] == before
        w.sync()                                 # explicit point syncs
        assert _stats()["syncs"] == before + 1
        assert w.maybe_sync() is False           # nothing unsynced
        w.close()

    def test_abandoned_appends_survive_replay(self, tmp_path):
        """The crashed-router simulation: abandon() skips the
        close-time fsync, but the unbuffered appends already reached
        the OS — replay sees every record."""
        d = str(tmp_path / "wal")
        w = J.JournalWriter(d, sync_ms=60_000)
        w.append(dict(ADMIT))
        w.append({"t": "dispatch", "id": "a", "rep": 0})
        w.abandon()
        st = J.replay(d)
        assert st.records == 2
        assert st.requests["a"]["replica"] == 0


# --------------------------------------------------------- compaction ----

class TestCompaction:
    def test_compact_bounds_footprint(self, tmp_path):
        d = str(tmp_path / "wal")
        w = J.JournalWriter(d, sync_ms=0, segment_bytes=512)
        for i in range(64):
            w.append(dict(ADMIT, id=f"r{i}"))
            w.append({"t": "done", "id": f"r{i}", "tokens": [1],
                      "finish_reason": "length"})
        assert w.compaction_due()
        grown = w.size_bytes()
        # the owner's snapshot retains only live state — here, nothing
        snapshot = [dict(ADMIT, id="live")]
        w.compact(snapshot)
        assert len(J.segment_paths(d)) == 1      # old segments unlinked
        assert w.size_bytes() < grown / 4
        # the size gauge tracks the compacted total
        assert metrics.gauge("journal.size_bytes").value \
            == w.size_bytes()
        st = J.replay(d)
        assert list(st.requests) == ["live"]     # acked ids dropped
        # appends keep working in the new segment
        w.append(dict(ADMIT, id="after"))
        w.close()
        assert set(J.replay(d).requests) == {"live", "after"}


# ------------------------------------------- torn tails + corruption ----

class TestTornTail:
    def test_truncated_final_record_discarded(self, tmp_path):
        d = str(tmp_path / "wal")
        path = _write_segment(d, [dict(ADMIT, id=f"r{i}")
                                  for i in range(3)])
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 7)                 # tear the last record
        before = _stats()
        st = J.replay(d)
        assert set(st.requests) == {"r0", "r1"}  # every intact record
        after = _stats()
        assert after["torn_tails"] == before["torn_tails"] + 1
        assert after["corrupt_records"] == before["corrupt_records"]

    def test_corrupt_length_prefix_stops_segment(self, tmp_path):
        d = str(tmp_path / "wal")
        path = _write_segment(d, [dict(ADMIT, id="r0"),
                                  dict(ADMIT, id="r1")])
        first = len(J.encode_record(dict(ADMIT, id="r0")))
        with open(path, "r+b") as f:
            f.seek(first)
            f.write(b"\xff\xff\xff\xff")         # length > MAX_RECORD
        before = _stats()["torn_tails"]
        st = J.replay(d)
        assert set(st.requests) == {"r0"}
        assert _stats()["torn_tails"] == before + 1

    def test_injected_torn_write_spec_parses(self):
        faults.install("journal_torn_write:nth=3,code=9")
        assert faults.journal_torn_write() is None   # 1st append
        assert faults.journal_torn_write() is None   # 2nd
        assert faults.journal_torn_write() == 9      # fires on the 3rd


class TestCorruptRecord:
    def test_flipped_body_byte_skips_one_record(self, tmp_path):
        d = str(tmp_path / "wal")
        recs = [dict(ADMIT, id=f"r{i}") for i in range(3)]
        path = _write_segment(d, recs)
        first = len(J.encode_record(recs[0]))
        # flip one byte inside record 1's BODY (past its header)
        with open(path, "r+b") as f:
            f.seek(first + 12 + 5)
            b = f.read(1)
            f.seek(first + 12 + 5)
            f.write(bytes([b[0] ^ 0xFF]))
        before = _stats()
        st = J.replay(d)
        assert set(st.requests) == {"r0", "r2"}  # later records intact
        after = _stats()
        assert after["corrupt_records"] \
            == before["corrupt_records"] + 1
        assert after["torn_tails"] == before["torn_tails"]

    def test_injected_corruption_detected_on_replay(self, tmp_path):
        """The writer-side fault flips a byte AFTER the digest stamp —
        replay must skip exactly that record and keep the rest."""
        d = str(tmp_path / "wal")
        faults.install("journal_corrupt_record:nth=2")
        w = J.JournalWriter(d, sync_ms=0)
        for i in range(3):
            w.append(dict(ADMIT, id=f"r{i}"))
        w.close()
        before = _stats()["corrupt_records"]
        st = J.replay(d)
        assert set(st.requests) == {"r0", "r2"}
        assert _stats()["corrupt_records"] == before + 1


# --------------------------------------------------- resume-time math ----

class TestResumeSubmitT:
    def test_burned_budget_stays_burned(self):
        # admitted 3s before the crash: the rebuilt submit_t sits 3s in
        # this process's past, so a 4s deadline has ~1s left
        t = J.resume_submit_t(97.0, now_wall=100.0, now_perf=50.0)
        assert t == pytest.approx(47.0)

    def test_future_stamp_clamps_to_now(self):
        # clock skew must never mint EXTRA budget
        t = J.resume_submit_t(105.0, now_wall=100.0, now_perf=50.0)
        assert t == pytest.approx(50.0)


# ------------------------------------------------- state semantics ----

class TestStateSemantics:
    def test_orphan_done_recovers_result_not_lost(self):
        st = J.JournalState()
        st.apply({"t": "done", "id": "x", "tokens": [1, 2],
                  "finish_reason": "eos"})
        assert st.requests["x"]["status"] == "done"
        assert st.lost_ids() == []               # the RESULT survived

    def test_orphan_lifecycle_without_admit_is_lost(self):
        st = J.JournalState()
        st.apply({"t": "dispatch", "id": "y", "rep": 0})
        assert st.lost_ids() == ["y"]            # nothing to re-serve

    def test_flip_preserves_handoff_stamp_not_bytes(self):
        st = J.JournalState()
        st.apply(dict(ADMIT, phase="prefill"))
        st.apply({"t": "flip", "id": "a", "first_token": 9,
                  "kv_bytes": 4096, "kv_hash": "h" * 32,
                  "prefill_replica": 0})
        v = st.requests["a"]
        assert v["phase"] == "decode" and v["first_token"] == 9
        assert v["kv_hash"] == "h" * 32 and v["kv_bytes"] == 4096
        assert "kv" not in v                     # bytes never journaled

    def test_admit_merges_into_orphan_skeleton(self):
        st = J.JournalState()
        st.apply({"t": "done", "id": "a", "tokens": [3],
                  "finish_reason": "length"})
        st.apply(dict(ADMIT))                    # checkpoint order quirk
        v = st.requests["a"]
        assert v["status"] == "done" and v["rec"] is not None
        assert len(st.order) == 1
