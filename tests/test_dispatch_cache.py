"""The eager fast paths: jit-cached dispatch + fused optimizer step.

Covers the tentpole contract: steady-state eager loops re-trace nothing
(cache hit/miss behavior across shape/dtype/amp changes), the fused
optimizer step is numerically identical to the per-param eager loop
(incl. grad clip + weight decay), double-grad works through cached
primitives, and impure primitives (host RNG) transparently fall back.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import dispatch
from paddle_tpu.optimizer import optimizer as opt_mod


@pytest.fixture(autouse=True)
def _fresh_cache():
    dispatch.clear_cache()
    dispatch.reset_cache_stats()
    opt_mod.reset_fused_stats()
    # compile on first sighting so the keying tests can count misses
    # deterministically; the warm-up default is covered by its own test
    os.environ["PADDLE_TPU_DISPATCH_CACHE_WARMUP"] = "1"
    yield
    for k in ("PADDLE_TPU_FUSED_STEP", "PADDLE_TPU_DISPATCH_CACHE",
              "PADDLE_TPU_DISPATCH_CACHE_SIZE",
              "PADDLE_TPU_DISPATCH_CACHE_WARMUP"):
        os.environ.pop(k, None)


def _t(arr, sg=True):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=sg)


# ------------------------------------------------------------ hit/miss keying

def test_steady_state_loop_stops_tracing():
    x = _t(np.random.randn(8, 8).astype("float32"), sg=False)
    w = _t(np.random.randn(8, 8).astype("float32"), sg=False)
    for i in range(6):
        y = (x.matmul(w) + 1.0).sum()
        y.backward()
        if i == 1:
            warm = dispatch.cache_stats()["misses"]
    s = dispatch.cache_stats()
    assert s["misses"] == warm, "steady-state steps retraced"
    assert s["hits"] > 0 and s["fallbacks"] == 0


def test_shape_and_dtype_changes_each_get_one_entry():
    a32 = _t(np.ones((4, 4), "float32"))
    b32 = _t(np.ones((4, 4), "float32"))
    (a32 + b32)
    m0 = dispatch.cache_stats()["misses"]
    (a32 + b32)
    assert dispatch.cache_stats()["misses"] == m0          # hit
    c = _t(np.ones((2, 8), "float32"))
    (c + c)                                                # shape -> miss
    assert dispatch.cache_stats()["misses"] == m0 + 1
    d = _t(np.ones((4, 4), "int32"))
    (d + d)                                                # dtype -> miss
    assert dispatch.cache_stats()["misses"] == m0 + 2
    (c + c); (d + d)                                       # both warm now
    assert dispatch.cache_stats()["misses"] == m0 + 2


def test_amp_state_is_part_of_the_key():
    x = _t(np.ones((4, 4), "float32"))
    w = _t(np.ones((4, 4), "float32"))
    x.matmul(w)
    m0 = dispatch.cache_stats()["misses"]
    with paddle.amp.auto_cast():
        out = x.matmul(w)
        assert str(out.dtype) == "bfloat16"
        assert dispatch.cache_stats()["misses"] == m0 + 1  # new amp entry
        x.matmul(w)
        assert dispatch.cache_stats()["misses"] == m0 + 1  # amp-keyed hit
    out2 = x.matmul(w)                                     # back outside
    assert str(out2.dtype) == "float32"
    assert dispatch.cache_stats()["misses"] == m0 + 1


def test_scalar_float_operand_changes_do_not_retrace():
    x = _t(np.ones((4,), "float32"))
    for s in (0.5, 1.5, 2.5):
        out = x * s
    np.testing.assert_allclose(out.numpy(), 2.5 * np.ones(4), rtol=1e-6)
    assert dispatch.cache_stats()["misses"] == 1


def test_grad_mode_gets_its_own_entry_and_grads_match_uncached():
    x = _t(np.array([1.0, 2.0, 3.0], "float32"), sg=False)
    y = (x * x).sum()
    y.backward()
    g_cached = np.array(x.grad.numpy())
    x.clear_grad()
    os.environ["PADDLE_TPU_DISPATCH_CACHE"] = "0"
    y2 = (x * x).sum()
    y2.backward()
    np.testing.assert_allclose(g_cached, x.grad.numpy(), rtol=1e-6)


def test_warmup_gates_one_shot_signatures():
    os.environ["PADDLE_TPU_DISPATCH_CACHE_WARMUP"] = "2"
    x = _t(np.ones((5,), "float32"))
    (x + x)                     # 1st sighting: plain eager, no compile
    s = dispatch.cache_stats()
    assert s["misses"] == 0 and s["warming"] == 1
    (x + x)                     # 2nd sighting: compiles
    assert dispatch.cache_stats()["misses"] == 1
    (x + x)                     # 3rd: hit
    s = dispatch.cache_stats()
    assert s["misses"] == 1 and s["hits"] == 1


def test_lru_bound_evicts():
    os.environ["PADDLE_TPU_DISPATCH_CACHE_SIZE"] = "2"
    for n in (1, 2, 3, 4):
        a = _t(np.ones((n, n), "float32"))
        (a + a)
    s = dispatch.cache_stats()
    assert s["evictions"] >= 2 and s["size"] <= 2


def test_host_rng_primitive_blacklists_and_stays_random():
    a = _t(np.ones((32, 32), "float32"))
    m1 = F.dropout(a, 0.5).numpy()
    m2 = F.dropout(a, 0.5).numpy()
    assert not np.array_equal(m1, m2), "cached dropout repeated its mask"
    assert dispatch.cache_stats()["blacklisted"] >= 1


def test_unhashable_closure_falls_back_correctly():
    idx = np.array([2, 0, 1])
    mask = _t(np.array([1.0, 0.0, 1.0], "float32"))

    def pick(v):
        # closure cell holds a Tensor -> no sound key -> eager fallback
        return v * mask.value

    x = _t(np.array([1.0, 2.0, 3.0], "float32"))
    out = dispatch.call(pick, x, _name="pick")
    np.testing.assert_allclose(out.numpy(), [1.0, 0.0, 3.0])
    assert dispatch.cache_stats()["fallbacks"] >= 1
    del idx


def test_double_grad_through_cached_primitive():
    def second_order(cache):
        os.environ["PADDLE_TPU_DISPATCH_CACHE"] = cache
        x = _t(np.array([1.5, -2.0, 3.0], "float32"), sg=False)
        y = (x * x * x).sum()
        (gx,) = paddle.grad(y, x, create_graph=True)
        z = (gx * gx).sum()
        z.backward()
        return np.array(x.grad.numpy())

    np.testing.assert_allclose(second_order("1"), second_order("0"),
                               rtol=1e-6)


def test_static_mode_flip_invalidates():
    a = _t(np.ones((4,), "float32"))
    (a + a)
    assert dispatch.cache_stats()["size"] > 0
    paddle.enable_static()
    try:
        assert dispatch.cache_stats()["size"] == 0
    finally:
        paddle.disable_static()


# ------------------------------------------------------------ fused optimizer

def _train(opt_name, fused, steps=6):
    os.environ["PADDLE_TPU_FUSED_STEP"] = "1" if fused else "0"
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(10, 16), nn.Tanh(), nn.Linear(16, 4))
    kw = dict(learning_rate=0.05, parameters=net.parameters(),
              grad_clip=paddle.nn.ClipGradByGlobalNorm(0.7))
    if opt_name in ("Adam", "AdamW"):
        kw["weight_decay"] = 0.02
    opt = getattr(paddle.optimizer, opt_name)(**kw)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(8, 10).astype("float32"))
    for _ in range(steps):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return [np.asarray(p.numpy()) for p in net.parameters()], opt


@pytest.mark.parametrize("opt_name", ["Adam", "AdamW", "Adadelta"])
def test_fused_step_matches_eager_loop(opt_name):
    fused_params, _ = _train(opt_name, True)
    stats = dict(opt_mod._fused_stats)
    eager_params, _ = _train(opt_name, False)
    for a, b in zip(fused_params, eager_params):
        np.testing.assert_allclose(a, b, atol=1e-6)
    assert stats["compiles"] == 1, stats      # one executable total
    assert stats["calls"] == 6, stats         # exactly 1 call per step


def test_fused_step_one_call_regardless_of_param_count():
    paddle.seed(0)
    net = nn.Sequential(*[nn.Linear(6, 6) for _ in range(9)])
    assert len(net.parameters()) == 18
    opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
    x = paddle.to_tensor(np.ones((2, 6), "float32"))
    for _ in range(4):
        loss = net(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    s = dict(opt_mod._fused_stats)
    assert s["compiles"] == 1 and s["calls"] == 4, s


def test_fused_respects_param_groups_and_no_grad():
    def run(fused):
        os.environ["PADDLE_TPU_FUSED_STEP"] = "1" if fused else "0"
        paddle.seed(1)
        a, b = nn.Linear(5, 5), nn.Linear(5, 5)
        opt = paddle.optimizer.Momentum(0.1, parameters=[
            {"params": a.parameters(), "learning_rate": 0.5},
            {"params": b.parameters(), "weight_decay": 0.01},
        ])
        x = paddle.to_tensor(np.ones((3, 5), "float32"))
        for _ in range(3):
            loss = (a(x) + b(x)).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return [np.asarray(p.numpy())
                for p in a.parameters() + b.parameters()]

    for f, e in zip(run(True), run(False)):
        np.testing.assert_allclose(f, e, atol=1e-6)


def test_fused_state_dict_roundtrip_matches():
    # auto-generated param names differ between the two builds — compare
    # accumulators positionally through each optimizer's own param list
    _, opt_f = _train("Adam", True, steps=3)
    _, opt_e = _train("Adam", False, steps=3)
    assert opt_f._step_count == opt_e._step_count == 3
    for pf, pe in zip(opt_f._parameters, opt_e._parameters):
        for nm in opt_f._accum_names:
            np.testing.assert_allclose(
                np.asarray(opt_f._accumulators[nm][id(pf)]),
                np.asarray(opt_e._accumulators[nm][id(pe)]),
                atol=1e-6, err_msg=nm)


def test_gradient_merge_fused_accumulation():
    from paddle_tpu.optimizer.gradient_merge import GradientMergeOptimizer
    paddle.seed(3)
    net = nn.Linear(4, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters())
    gm = GradientMergeOptimizer(inner, k_steps=2, avg=True)
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    w0 = np.array(net.weight.numpy())
    for _ in range(4):
        loss = net(x).sum()
        loss.backward()
        gm.step()
    g = np.full((4, 4), 2.0, np.float32)       # d(sum)/dW for all-ones x
    np.testing.assert_allclose(net.weight.numpy(),
                               w0 - 0.1 * g - 0.1 * g, atol=1e-6)


def test_profiler_surfaces_fast_path_counters():
    from paddle_tpu import profiler
    a = _t(np.ones((4,), "float32"))
    (a + a); (a + a)
    s = profiler.fast_path_summary()
    assert s["dispatch_cache"]["hits"] >= 1
    assert "calls" in s["fused_step"]
