"""Serving-fleet tests (ISSUE 7): the router's durability contract
(re-queue on crash, dedupe, shedding, deadlines), the engine's
slot-leak-on-failure fix, stable request ids in telemetry, the
serving-fault injection hooks, and the richer launcher incident
records.

Subprocess fleets use a deliberately tiny GPT so each replica boots in
a couple of seconds on the CPU backend; everything else is in-process.
"""
import glob
import json
import os
import socket
import time

import numpy as np
import pytest

from paddle_tpu.testing import faults
from paddle_tpu.testing.env import clean_cpu_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CFG = {"vocab_size": 256, "hidden_size": 32, "num_layers": 2,
            "num_heads": 2, "max_seq_len": 128, "dtype": "float32",
            "use_flash": False, "remat": False}
SPEC = {"cfg": TINY_CFG, "seed": 0, "slots": 2, "max_len": 96,
        "seq_buckets": [8], "batch_buckets": [1, 2]}


def _engine(slots=2, max_len=32, **kw):
    import jax
    from paddle_tpu.models import gpt as G
    from paddle_tpu.inference.serving import ServingEngine
    cfg = G.GPTConfig(**TINY_CFG)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    return ServingEngine((params, cfg), slots=slots, max_len=max_len,
                         seq_buckets=(8,), batch_buckets=(1, 2), **kw)


def _fleet(tmp_path, tag, replicas=2, fault_spec=None, **kw):
    from paddle_tpu.inference.fleet import ServingFleet
    env = clean_cpu_env(REPO, device_count=1)
    env.pop("PADDLE_FAULTS", None)
    if fault_spec:
        env["PADDLE_FAULTS"] = fault_spec
    kw.setdefault("heartbeat_s", 20)
    kw.setdefault("restart_backoff_s", 0.2)
    return ServingFleet(SPEC, replicas=replicas, env_base=env,
                        log_dir=str(tmp_path / tag / "logs"), **kw)


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------ wire protocol ----

class TestFraming:
    def test_roundtrip(self):
        from paddle_tpu.inference.fleet import recv_msg, send_msg
        a, b = socket.socketpair()
        try:
            send_msg(a, {"op": "step", "ids": list(range(50))})
            out = recv_msg(b)
            assert out["op"] == "step" and len(out["ids"]) == 50
        finally:
            a.close()
            b.close()

    def test_eof_raises_connection_error(self):
        from paddle_tpu.inference.fleet import recv_msg
        a, b = socket.socketpair()
        a.close()
        with pytest.raises(ConnectionError):
            recv_msg(b)
        b.close()

    def test_oversize_frame_rejected(self):
        import struct
        from paddle_tpu.inference.fleet import recv_msg
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">I", 1 << 30))
            with pytest.raises(ConnectionError, match="oversized"):
                recv_msg(b)
        finally:
            a.close()
            b.close()


# ------------------------------------------------ engine slot-leak fix ----

class TestEngineAbort:
    def test_mid_step_failure_frees_slots_and_marks_requeueable(self):
        """Satellite regression: a decode step raising must not leave
        in-flight requests pinning their slots forever — occupancy
        recovers, the victims are failed/re-queueable, and the SAME
        engine serves the retries token-exactly."""
        eng = _engine()
        r1 = eng.submit(np.arange(1, 6, dtype=np.int32), 6)
        r2 = eng.submit(np.arange(1, 4, dtype=np.int32), 6)
        eng.step()
        want1, want2 = list(r1.tokens), list(r2.tokens)
        assert eng.stats()["slot_occupancy"] == 2
        faults.install("engine_error:step=2")
        with pytest.raises(faults.InjectedFault):
            eng.step()
        st = eng.stats()
        assert st["slot_occupancy"] == 0, st       # the leak, fixed
        assert st["step_aborts"] == 1
        assert st["requests_aborted"] == 2
        aborted = eng.take_aborted()
        assert {a.id for a in aborted} == {r1.id, r2.id}
        assert all(a.failed and not a.done and a.error for a in aborted)
        assert eng.take_aborted() == []            # drained exactly once
        # the engine keeps serving, and retries are token-exact
        for a in aborted:
            eng.submit(a.reset_for_retry())
        done = eng.run()
        assert len(done) == 2
        assert r1.tokens[:len(want1)] == want1
        assert r2.tokens[:len(want2)] == want2
        assert len(r1.tokens) == 6 and len(r2.tokens) == 6

    def test_completion_before_failure_survives_on_backlog(self):
        """A request that COMPLETES inside a step that later raises must
        not vanish with the exception: it stays on the finished backlog
        and the next step()/take_finished() delivers it (a crash never
        un-completes a request)."""
        eng = _engine()
        # finishes during ADMISSION (prefill's first sampled token is
        # its whole budget); the decode fault then fails the same step()
        quick = eng.submit(np.arange(1, 6, dtype=np.int32), 1)
        slow = eng.submit(np.arange(1, 4, dtype=np.int32), 8)
        faults.install("engine_error:step=1")
        with pytest.raises(faults.InjectedFault):
            eng.step()
        assert quick.done and len(quick.tokens) == 1
        delivered = eng.take_finished()
        assert delivered == [quick]
        aborted = eng.take_aborted()
        assert aborted == [slow] and slow.failed

    def test_prefill_failure_aborts_admitting_group(self, monkeypatch):
        """A prefill blowing up AFTER its group left the queue must mark
        that group re-queueable too — not silently lose it."""
        eng = _engine()

        def boom(*a, **k):
            raise RuntimeError("device exploded in prefill")
        monkeypatch.setattr(eng, "_build_prefill",
                            lambda b, s: boom)
        r = eng.submit(np.arange(1, 6, dtype=np.int32), 4)
        with pytest.raises(RuntimeError, match="device exploded"):
            eng.step()
        assert eng.stats()["slot_occupancy"] == 0
        aborted = eng.take_aborted()
        assert aborted and aborted[0].id == r.id
        assert r.failed and "device exploded" in r.error

    def test_abort_rebuilds_cache_and_occupancy_gauge(self):
        from paddle_tpu.observability import metrics
        eng = _engine()
        eng.submit(np.arange(1, 6, dtype=np.int32), 8)
        eng.step()
        k_before = eng._cache_k
        faults.install("engine_error:step=2")
        with pytest.raises(faults.InjectedFault):
            eng.step()
        assert eng._cache_k is not k_before        # fresh donated pool
        assert metrics.gauge("serving.slot_occupancy").value == 0


# ------------------------------------------------ stable request ids ----

class TestRequestIds:
    def test_auto_uuid_and_client_supplied(self):
        from paddle_tpu.inference.serving import Request
        a = Request([1, 2], 2)
        b = Request([1, 2], 2)
        assert isinstance(a.id, str) and len(a.id) == 32
        assert a.id != b.id
        c = Request([1, 2], 2, request_id="client-7")
        assert c.id == "client-7"

    def test_ids_surface_in_jsonl_events(self, tmp_path):
        from paddle_tpu.observability import timeline
        timeline.configure(str(tmp_path))
        try:
            eng = _engine()
            r = eng.submit(np.arange(1, 6, dtype=np.int32), 3,
                           request_id="ride-along")
            eng.run()
            assert r.done
        finally:
            timeline.configure(None)
        recs = []
        for path in glob.glob(str(tmp_path / "events_rank*.jsonl")):
            with open(path) as f:
                recs += [json.loads(line) for line in f if line.strip()]
        steps = [x for x in recs if x.get("event") == "serving_step"]
        assert any("ride-along" in (x.get("finished_ids") or [])
                   for x in steps), steps
        comp = [x for x in recs if x.get("event") == "request_complete"]
        assert any(x["request_id"] == "ride-along"
                   and x["finish_reason"] == "length"
                   and x["latency_s"] > 0 for x in comp), comp

    def test_replica_label_on_latency_histogram(self, monkeypatch):
        from paddle_tpu.observability import metrics
        monkeypatch.setenv("PADDLE_FLEET_REPLICA", "9")
        eng = _engine()
        eng.submit(np.arange(1, 4, dtype=np.int32), 2)
        eng.run()
        h = metrics.histogram("serving.request_latency_s", replica="9")
        assert h.count >= 1


# ----------------------------------------------------- fault hooks ----

class TestServingFaultHooks:
    def test_rpc_delay_sleeps_and_drop_signals(self):
        faults.install("rpc_delay:op=step,seconds=0.05")
        t0 = time.perf_counter()
        dropped = faults.rpc_entry("step")
        assert time.perf_counter() - t0 >= 0.05
        assert dropped is False
        faults.install("rpc_drop:op=step")
        assert faults.rpc_entry("step") is True
        assert faults.rpc_entry("step") is False   # fired once, disarmed

    def test_replica_kill_filters_on_request_count(self):
        f = faults.install("replica_kill:request=3")[0]
        assert faults.take("replica_kill", request=1) is None
        assert faults.take("replica_kill", request=2) is None
        assert faults.take("replica_kill", request=3) is f
        # step-scoped spec never matches a request-only call site
        faults.clear()
        faults.install("replica_kill:step=2")
        assert faults.take("replica_kill", request=2) is None

    def test_engine_error_hook_raises_injected(self):
        faults.install("engine_error:step=5")
        faults.engine_step_error(4)                # no-op off the mark
        with pytest.raises(faults.InjectedFault):
            faults.engine_step_error(5)


# ------------------------------------------------- launcher incidents ----

class TestIncidentRecords:
    def test_supervise_incidents_carry_signal_and_wall_time(self, tmp_path):
        """Satellite: the exit summary's per-incident records name the
        failing rank, decoded signal/rc, wall time and restart count."""
        import importlib
        launch = importlib.import_module("paddle_tpu.distributed.launch")
        script = tmp_path / "die.py"
        script.write_text("import os, signal; os.kill(os.getpid(), "
                          "signal.SIGKILL)\n")
        env = clean_cpu_env(REPO, device_count=1)
        summary = launch.supervise([str(script)], nprocs=1, env_base=env,
                                   max_restarts=1, backoff=0.05)
        assert summary["rc"] == -9
        assert len(summary["incidents"]) == 2
        for i, inc in enumerate(summary["incidents"]):
            assert inc["rank"] == 0
            assert inc["exit_code"] == -9
            assert inc["signal"] == "SIGKILL"
            assert inc["restart_count"] == i
            assert inc["wall_time_s"] is not None \
                and inc["wall_time_s"] >= 0


# ------------------------------------------- router-side fixes (I11) ----

def _stub_fleet(tmp_path, tag, worker_src, replicas=1, **kw):
    """A fleet over trivial non-jax workers: router state machinery
    without an engine boot."""
    from paddle_tpu.inference.fleet import ServingFleet
    env = clean_cpu_env(REPO, device_count=1)
    env.pop("PADDLE_FAULTS", None)
    kw.setdefault("heartbeat_s", 5)
    kw.setdefault("spawn_timeout_s", 120)
    return ServingFleet(SPEC, replicas=replicas, env_base=env,
                        log_dir=str(tmp_path / tag / "logs"),
                        worker_argv=["-c", worker_src], **kw)


class TestQueuedDeadlineSweep:
    def test_never_dispatched_request_fails_at_deadline(self, tmp_path):
        """ISSUE 11 satellite regression: a request stuck in the ROUTER
        queue (here: no replica ever finishes booting, so nothing is
        ever dispatched) must fail named at its deadline — the sweep
        covers the queued set, not just the per-replica in-flight
        tables."""
        fleet = _stub_fleet(tmp_path, "qdl",
                            "import time; time.sleep(300)")
        try:
            req = fleet.submit([1, 2, 3], 8, request_id="stuck",
                               deadline_s=0.2)
            deadline = time.time() + 5
            while not req.failed and time.time() < deadline:
                time.sleep(0.01)
            assert req.failed and "deadline_exceeded" in req.error, (
                req.failed, req.error)
            st = fleet.stats()
            assert st["deadline_exceeded"] >= 1
            assert "stuck" in fleet._failed and not fleet._pending
        finally:
            fleet.close()


class TestShutdownInterruptsBackoff:
    def test_shutdown_during_restart_backoff_returns_fast(self, tmp_path):
        """ISSUE 11 satellite regression: shutdown() during a replica's
        restart-backoff window must wake the driver thread off the stop
        event immediately — never sleep out the (here: 20s) backoff."""
        fleet = _stub_fleet(tmp_path, "bko", "raise SystemExit(1)",
                            restart_backoff_s=20.0, max_restarts=5)
        try:
            # the worker dies instantly; wait until the replica is DEAD
            # and parked inside its first 20s backoff window
            r = fleet._replicas[0]
            deadline = time.time() + 30
            while (r.state != "dead" or not fleet.incidents) \
                    and time.time() < deadline:
                time.sleep(0.01)
            assert r.state == "dead" and fleet.incidents
            assert r.next_spawn_t > time.monotonic() + 5, \
                "replica is not in a long backoff window"
        finally:
            t0 = time.perf_counter()
            fleet.shutdown()            # the close() production alias
            elapsed = time.perf_counter() - t0
        assert elapsed < 5.0, (
            f"shutdown blocked {elapsed:.1f}s — backoff sleep is not "
            "interruptible")


# ------------------------------------------------- subprocess fleets ----

def _tiny_prompts(n, seed=0, tokens=24):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, 256, int(rng.randint(3, 8))), tokens)
            for _ in range(n)]


class TestFleet:
    def test_serves_dedupes_sheds_and_deadline(self, tmp_path):
        """One boot, several contracts: completion, id dedupe, load
        shedding past max_pending, per-request deadline failure."""
        from paddle_tpu.inference.fleet import FleetOverloaded
        fleet = _fleet(tmp_path, "basic", max_pending=64)
        try:
            assert fleet.await_healthy(timeout=120) == 2
            reqs = [fleet.submit(p, m, request_id=f"r{i}")
                    for i, (p, m) in enumerate(_tiny_prompts(8))]
            # dedupe: same id returns the SAME pending record
            again = fleet.submit([9, 9, 9], 4, request_id="r0")
            assert again is reqs[0]
            done, failed = fleet.drain(timeout=120)
            assert not failed and len(done) == 8
            assert all(len(done[f"r{i}"].tokens) == 24 for i in range(8))
            # dedupe after completion: the finished record comes back
            assert fleet.submit([9], 4, request_id="r0") is reqs[0]
            # shedding: a tiny pending bound rejects fast
            fleet.max_pending = 1
            fleet.submit([1, 2, 3], 64, request_id="s0")
            with pytest.raises(FleetOverloaded):
                fleet.submit([1, 2, 3], 64, request_id="s1")
            assert fleet.stats()["sheds"] == 1
            fleet.max_pending = 64
            # deadline: an expired request fails NAMED, never silent
            d = fleet.submit([5, 5, 5], 64, request_id="dl",
                             deadline_s=0.0)
            deadline = time.time() + 30
            while "dl" not in fleet._failed and time.time() < deadline:
                time.sleep(0.01)
            assert d.failed and "deadline_exceeded" in d.error
            done, failed = fleet.drain(timeout=120)
            assert "dl" in failed and not d.tokens
            st = fleet.stats()
            assert st["deadline_exceeded"] >= 1
            assert st["requests_completed"] >= 9    # s0 still served
        finally:
            fleet.close()

    @pytest.mark.slow      # ~25s subprocess e2e; tier-1 budget
    def test_replica_sigkill_requeues_with_token_parity(self, tmp_path):
        """The tentpole invariant, in-tree: SIGKILL a replica holding
        in-flight requests; nothing is lost, the re-queued requests'
        tokens match an in-process reference engine exactly, and the
        replacement replica comes back."""
        import jax
        from paddle_tpu.models import gpt as G
        cfg = G.GPTConfig(**TINY_CFG)
        params = G.init_params(cfg, jax.random.PRNGKey(0))
        prompts = _tiny_prompts(12, seed=5, tokens=48)
        ref = {f"r{i}": [int(t) for t in np.asarray(
            G.generate(params, cfg, np.asarray(p)[None], m))[0, len(p):]]
            for i, (p, m) in enumerate(prompts)}

        fleet = _fleet(tmp_path, "chaos")
        try:
            assert fleet.await_healthy(timeout=120) == 2
            for i, (p, m) in enumerate(prompts):
                fleet.submit(p, m, request_id=f"r{i}")
            victim = fleet._replicas[0]
            deadline = time.time() + 15
            while not victim.inflight and time.time() < deadline:
                time.sleep(0.002)
            assert victim.inflight, "victim never got work"
            fleet.kill_replica(0)
            done, failed = fleet.drain(timeout=180)
            assert not failed and len(done) == 12, (len(done), failed)
            st = fleet.stats()
            assert st["incidents"] >= 1 and st["requeues"] >= 1
            for rid, want in ref.items():
                assert done[rid].tokens == want, rid
            assert fleet.await_healthy(timeout=120) == 2
            assert fleet.recovery_time_s() is not None
        finally:
            fleet.close()

    def test_worker_engine_error_requeues_without_restart(self, tmp_path):
        """A mid-step engine failure inside a replica must NOT need a
        replica restart: the worker aborts, hands the victims back, the
        router re-queues them, everything completes."""
        fleet = _fleet(tmp_path, "engerr",
                       fault_spec="engine_error:step=3,rank=0")
        try:
            assert fleet.await_healthy(timeout=120) == 2
            for i, (p, m) in enumerate(_tiny_prompts(8, seed=2,
                                                     tokens=32)):
                fleet.submit(p, m, request_id=f"r{i}")
            done, failed = fleet.drain(timeout=180)
            assert not failed and len(done) == 8
            st = fleet.stats()
            assert st["requeues"] >= 1, st
            assert st["replica_restarts"] == 0, st
        finally:
            fleet.close()

    def test_rpc_drop_recovers_without_losing_completions(self, tmp_path):
        """An injected dropped RPC reply (replica vanishes mid-answer)
        runs the incident path; any completion riding the lost reply is
        re-delivered/re-served and deduped — zero lost."""
        fleet = _fleet(tmp_path, "drop",
                       fault_spec="rpc_drop:nth=4,op=step,rank=1")
        try:
            assert fleet.await_healthy(timeout=120) == 2
            for i, (p, m) in enumerate(_tiny_prompts(10, seed=3,
                                                     tokens=32)):
                fleet.submit(p, m, request_id=f"r{i}")
            done, failed = fleet.drain(timeout=180)
            assert not failed and len(done) == 10
            st = fleet.stats()
            assert st["incidents"] >= 1, st
        finally:
            fleet.close()
