"""Elastic serving fleet + SLO autoscaler tests (ISSUE 11): the
reproducible traffic generator, the autoscaler control law (against a
fake fleet — deterministic, no subprocesses), the priority-class
admission/shedding contract, and the elastic lifecycle e2e
(drain-then-stop scale-down under live traffic, warm scale-up, chaos
composition with slow-start + SIGKILL during scale-up).

Subprocess fleets use the same deliberately tiny GPT as
test_serving_fleet.py; router-only contracts (priority queues, queued
deadline sweep) use a stub worker that never says hello, so no jax
process is ever built for them.
"""
import os
import time

import numpy as np
import pytest

from paddle_tpu.testing import faults, traffic
from paddle_tpu.testing.env import clean_cpu_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CFG = {"vocab_size": 256, "hidden_size": 32, "num_layers": 2,
            "num_heads": 2, "max_seq_len": 128, "dtype": "float32",
            "use_flash": False, "remat": False}
SPEC = {"cfg": TINY_CFG, "seed": 0, "slots": 2, "max_len": 96,
        "seq_buckets": [8], "batch_buckets": [1, 2]}


def _fleet(tmp_path, tag, replicas=2, fault_spec=None, **kw):
    from paddle_tpu.inference.fleet import ServingFleet
    env = clean_cpu_env(REPO, device_count=1)
    env.pop("PADDLE_FAULTS", None)
    if fault_spec:
        env["PADDLE_FAULTS"] = fault_spec
    kw.setdefault("heartbeat_s", 20)
    kw.setdefault("restart_backoff_s", 0.2)
    return ServingFleet(SPEC, replicas=replicas, env_base=env,
                        log_dir=str(tmp_path / tag / "logs"), **kw)


def _stub_fleet(tmp_path, tag="stub", replicas=1, **kw):
    """A fleet whose workers sleep forever and never hello: router-side
    state machinery (queues, admission, deadline sweep) without paying
    a jax subprocess boot."""
    from paddle_tpu.inference.fleet import ServingFleet
    env = clean_cpu_env(REPO, device_count=1)
    env.pop("PADDLE_FAULTS", None)
    kw.setdefault("heartbeat_s", 5)
    kw.setdefault("spawn_timeout_s", 120)
    return ServingFleet(
        SPEC, replicas=replicas, env_base=env,
        log_dir=str(tmp_path / tag / "logs"),
        worker_argv=["-c", "import time; time.sleep(300)"], **kw)


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------ traffic shapes ----

class TestTraffic:
    KW = dict(duration_s=10.0, base_rate=6.0, seed=3,
              bursts=((0.3, 0.6, 3.0),), batch_fraction=0.3,
              prefix_hit_rate=0.5, prefix_len=3,
              prompt_len=(5, 0.5, 4, 8), output_tokens=(12, 0.5, 4, 32))

    def test_same_seed_same_traffic(self):
        a = traffic.generate(**self.KW)
        b = traffic.generate(**self.KW)
        assert len(a) == len(b) and len(a) > 20
        for x, y in zip(a, b):
            assert x.t == y.t and x.request_id == y.request_id
            assert np.array_equal(x.prompt, y.prompt)
            assert x.max_new_tokens == y.max_new_tokens
            assert x.priority == y.priority
        c = traffic.generate(**dict(self.KW, seed=4))
        assert [x.t for x in c] != [x.t for x in a]

    def test_burst_multiplies_local_rate(self):
        arr = traffic.generate(**dict(self.KW, duration_s=60.0,
                                      base_rate=8.0))
        burst = [a for a in arr if 18.0 <= a.t < 36.0]
        outside = [a for a in arr if not 18.0 <= a.t < 36.0]
        rate_in = len(burst) / 18.0
        rate_out = len(outside) / 42.0
        # 3x nominal; Poisson noise keeps this loose but unambiguous
        assert rate_in > 2.0 * rate_out, (rate_in, rate_out)
        assert all(a.t < 60.0 for a in arr)
        assert [a.t for a in arr] == sorted(a.t for a in arr)

    def test_lengths_clipped_and_priorities_mixed(self):
        arr = traffic.generate(**self.KW)
        assert all(4 <= len(a.prompt) <= 8 for a in arr)
        assert all(4 <= a.max_new_tokens <= 32 for a in arr)
        frac = sum(a.priority == "batch" for a in arr) / len(arr)
        assert 0.1 < frac < 0.55, frac
        assert {a.priority for a in arr} == {"interactive", "batch"}

    def test_prefix_hits_share_pool_bytes(self):
        arr = traffic.generate(**dict(self.KW, duration_s=30.0,
                                      prefix_pool=2))
        hits = [a for a in arr if a.prefix_hit]
        assert 0.25 < len(hits) / len(arr) < 0.75
        prefixes = {tuple(a.prompt[:3]) for a in hits}
        assert len(prefixes) <= 2          # drawn from the 2-entry pool
        # misses are unique-prefixed with overwhelming probability
        assert len({tuple(a.prompt[:3]) for a in arr
                    if not a.prefix_hit}) > 10

    def test_diurnal_ramp_modulates(self):
        kw = dict(self.KW, duration_s=60.0, bursts=(),
                  diurnal_amplitude=0.9, diurnal_period_s=60.0)
        arr = traffic.generate(**kw)
        # sin() peaks in the first half-period, troughs in the second
        first = sum(1 for a in arr if a.t < 30.0)
        second = len(arr) - first
        assert first > 1.5 * second, (first, second)

    def test_validation(self):
        with pytest.raises(ValueError, match="prefix_len"):
            traffic.TrafficSpec(prefix_hit_rate=0.5, prefix_len=8,
                                prompt_len=(5, 0.5, 4, 8))
        with pytest.raises(ValueError, match="batch_fraction"):
            traffic.TrafficSpec(batch_fraction=1.5)

    def test_replay_orders_and_paces(self):
        arr = traffic.generate(**dict(self.KW, duration_s=2.0,
                                      base_rate=10.0))
        seen = []
        t0 = time.perf_counter()
        n = traffic.replay(arr, lambda a: seen.append(
            (time.perf_counter() - t0, a.request_id)), speed=10.0)
        assert n == len(arr) == len(seen)
        assert [rid for _, rid in seen] == [a.request_id for a in arr]
        # 10x compression: the last arrival lands around t/10
        assert seen[-1][0] >= arr[-1].t / 10.0 - 0.01
        assert seen[-1][0] < arr[-1].t  # much faster than real time


# ------------------------------------------------- autoscaler control ----

class FakeFleet:
    """Just the surface Autoscaler.tick consumes — signals are set by
    the test, actions mutate a counter."""

    def __init__(self, n=1):
        self.n = n
        self.sig = dict(backlog=0, pending=0, pending_fraction=0.0,
                        healthy=None, occupancy=0.0, p99_s=None,
                        p50_s=None, window_n=0, sheds=0)
        self.added = 0
        self.removed = []
        self.raise_on_add = None

    def autoscale_signals(self, window_s):
        s = dict(self.sig)
        s["configured"] = self.n
        if s["healthy"] is None:
            s["healthy"] = self.n
        return s

    def add_replica(self):
        if self.raise_on_add is not None:
            raise self.raise_on_add
        self.n += 1
        self.added += 1
        return 100 + self.added

    def remove_replica(self, rid):
        self.n -= 1
        self.removed.append(rid)

    def scaledown_victim(self):
        return 7 if self.n > 1 else None


def _scaler(fleet, **kw):
    from paddle_tpu.inference.autoscale import Autoscaler
    kw.setdefault("slo_p99_s", 1.0)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("down_ticks", 3)
    return Autoscaler(fleet, **kw)


class TestAutoscalerControl:
    def test_scales_up_on_backlog_and_respects_cooldown(self):
        f = FakeFleet()
        a = _scaler(f)
        f.sig["backlog"] = 10
        assert a.tick(0.0) == "up" and f.n == 2
        assert a.tick(1.0) is None          # cooldown holds
        assert a.stats()["holds_cooldown"] >= 1
        assert a.tick(11.0) == "up" and f.n == 3

    def test_scales_up_on_p99_breach_and_pending_headroom(self):
        f = FakeFleet()
        a = _scaler(f, slo_p99_s=0.5)
        f.sig["p99_s"] = 0.9                # SLO breach
        assert a.tick(0.0) == "up"
        assert a.decisions[-1]["reasons"] == ["p99"]
        f = FakeFleet()
        a = _scaler(f)
        f.sig["pending_fraction"] = 0.8     # scale-up-BEFORE-shed
        assert a.tick(0.0) == "up"
        assert "pending" in a.decisions[-1]["reasons"]

    def test_backlog_normalized_by_accepted_tokens(self):
        # ISSUE 14 satellite: a speculative fleet reporting ~4.5
        # accepted tokens per row-step drains a queue ~4.5x faster, so
        # the SAME backlog that scales a non-spec fleet up must hold
        f = FakeFleet()
        a = _scaler(f)
        f.sig["backlog"] = 8                         # > 2.0 * 1 healthy
        f.sig["accepted_tokens_per_step"] = 4.5      # but < 2.0*1*4.5=9
        assert a.tick(0.0) is None
        # a backlog past even the token-normalized threshold still fires
        f.sig["backlog"] = 10
        assert a.tick(0.0) == "up"
        assert "backlog" in a.decisions[-1]["reasons"]
        assert a.decisions[-1]["signals"][
            "accepted_tokens_per_step"] == 4.5
        # non-speculative fleets (no signal / 0.0) keep today's law
        f2 = FakeFleet()
        a2 = _scaler(f2)
        f2.sig["backlog"] = 8
        assert a2.tick(0.0) == "up"

    def test_occupancy_needs_backlog(self):
        f = FakeFleet()
        a = _scaler(f)
        f.sig["occupancy"] = 1.0            # busy but keeping up
        assert a.tick(0.0) is None
        f.sig["backlog"] = 1
        f.sig["occupancy"] = 1.0
        f.sig["healthy"] = 2
        f.n = 2
        assert a.tick(0.0) == "up"
        assert "occupancy" in a.decisions[-1]["reasons"]

    def test_down_needs_hysteresis_streak(self):
        f = FakeFleet(n=3)
        a = _scaler(f, down_ticks=3)
        assert a.tick(0.0) is None          # idle streak 1
        assert a.tick(1.0) is None          # 2
        assert a.tick(2.0) == "down"        # 3 -> act
        assert f.removed == [7] and f.n == 2
        # a busy tick resets the streak
        assert a.tick(20.0) is None
        assert a.tick(21.0) is None
        f.sig["backlog"] = 1                # blip (not enough to scale)
        f.sig["occupancy"] = 0.5
        assert a.tick(22.0) is None
        f.sig["backlog"] = 0
        f.sig["occupancy"] = 0.0
        assert a.tick(23.0) is None         # streak restarted at 1
        assert f.n == 2

    def test_bounds_hold(self):
        f = FakeFleet(n=4)
        a = _scaler(f, max_replicas=4)
        f.sig["backlog"] = 100
        assert a.tick(0.0) is None          # at max: hold, counted
        assert a.stats()["holds_bounds"] >= 1
        f = FakeFleet(n=1)
        a = _scaler(f, min_replicas=1, down_ticks=1)
        assert a.tick(0.0) is None and f.n == 1

    def test_bounds_are_restorative_not_just_gates(self):
        """A fleet OUTSIDE [min, max] — operator removal, construction
        below the floor — is steered back even with no load signals."""
        f = FakeFleet(n=1)
        a = _scaler(f, min_replicas=3, max_replicas=4, cooldown_s=1.0)
        assert a.tick(0.0) == "up"          # idle, but below the floor
        assert a.decisions[-1]["reasons"] == ["bounds"]
        assert a.tick(0.5) is None          # restores honor cooldown
        assert a.tick(2.0) == "up" and f.n == 3
        f2 = FakeFleet(n=5)
        a2 = _scaler(f2, min_replicas=1, max_replicas=4)
        assert a2.tick(0.0) == "down" and f2.n == 4

    def test_flap_fault_forces_decisions_inside_bounds(self):
        f = FakeFleet(n=2)
        a = _scaler(f, min_replicas=1, max_replicas=3)
        faults.install("autoscale_flap:repeat=1")
        dirs = [a.tick(float(i)) for i in range(6)]
        assert set(d for d in dirs if d) <= {"up", "down"}
        assert a.stats()["flap_forced"] == 6
        assert 1 <= f.n <= 3                # bounds survived the storm
        faults.clear()
        faults.install("autoscale_flap:repeat=1,dir=up")
        f2 = FakeFleet(n=1)
        a2 = _scaler(f2, max_replicas=2)
        assert a2.tick(0.0) == "up"
        assert a2.tick(1.0) is None         # at max: bound holds
        assert f2.n == 2

    def test_tick_errors_do_not_wedge_the_loop(self):
        f = FakeFleet()
        a = _scaler(f)
        f.sig["backlog"] = 10
        f.raise_on_add = RuntimeError("spawn exploded")
        before = a.stats()["tick_errors"]
        assert a.tick(0.0) is None          # swallowed, counted
        assert a.stats()["tick_errors"] == before + 1
        f.raise_on_add = None
        assert a.tick(20.0) == "up"         # next tick recovers

    def test_start_stop_loop(self):
        f = FakeFleet()
        a = _scaler(f, interval_s=0.01)
        f.sig["backlog"] = 10
        with a:
            deadline = time.time() + 5
            while f.n < 2 and time.time() < deadline:
                time.sleep(0.01)
        assert f.n >= 2
        assert a._thread is None


# ----------------------------------------------------- new fault specs ----

class TestNewFaultSpecs:
    def test_slow_start_sleeps(self):
        faults.install("replica_slow_start:seconds=0.1")
        t0 = time.perf_counter()
        faults.slow_start_check()
        assert time.perf_counter() - t0 >= 0.1
        t0 = time.perf_counter()
        faults.slow_start_check()           # fired once, disarmed
        assert time.perf_counter() - t0 < 0.05

    def test_autoscale_flap_alternates_and_pins(self):
        faults.install("autoscale_flap:repeat=1")
        seq = [faults.autoscale_flap() for _ in range(4)]
        assert seq == ["up", "down", "up", "down"]
        faults.clear()
        faults.install("autoscale_flap:dir=down")
        assert faults.autoscale_flap() == "down"
        assert faults.autoscale_flap() is None    # disarmed


# ------------------------------------------- priority classes (router) ----

class TestPriorityAdmission:
    def test_weighted_fair_pop_interleaves(self, tmp_path):
        fleet = _stub_fleet(tmp_path, "wf", max_pending=64)
        try:
            for i in range(8):
                fleet.submit([1, i + 1], 4, request_id=f"i{i}")
            for i in range(4):
                fleet.submit([2, i + 1], 4, request_id=f"b{i}",
                             priority="batch")
            with fleet._lock:
                order = [fleet._pop_ready_locked().id for _ in range(12)]
            assert order == ["i0", "i1", "i2", "i3", "b0",
                             "i4", "i5", "i6", "i7", "b1", "b2", "b3"]
        finally:
            fleet.close()

    def test_interactive_displaces_queued_batch(self, tmp_path):
        from paddle_tpu.inference.fleet import FleetOverloaded
        fleet = _stub_fleet(tmp_path, "disp", max_pending=2)
        try:
            b0 = fleet.submit([1, 1], 4, request_id="b0",
                              priority="batch")
            b1 = fleet.submit([1, 2], 4, request_id="b1",
                              priority="batch")
            i0 = fleet.submit([1, 3], 4, request_id="i0")
            # the NEWEST queued batch request made room, failed named
            assert b1.failed and "shed_overload" in b1.error
            assert not b0.failed and not i0.failed
            st = fleet.stats()
            assert st["sheds"] == 1 and st["sheds_batch"] == 1
            assert st["sheds_interactive"] == 0
            # batch never displaces anything
            with pytest.raises(FleetOverloaded):
                fleet.submit([1, 4], 4, request_id="b2",
                             priority="batch")
            assert fleet.stats()["sheds_batch"] == 2
        finally:
            fleet.close()

    def test_interactive_displaces_inflight_batch_via_cancel(self, tmp_path):
        from paddle_tpu.inference.fleet import FleetRequest
        fleet = _stub_fleet(tmp_path, "inflight", max_pending=1)
        try:
            r = fleet._replicas[0]
            bq = FleetRequest([1, 1], 4, request_id="bq",
                              priority="batch")
            with fleet._lock:
                fleet._pending["bq"] = bq
                r.inflight["bq"] = bq       # dispatched, no queued batch
            i0 = fleet.submit([1, 3], 4, request_id="i0")
            assert bq.failed and "shed_overload" in bq.error
            assert "bq" not in r.inflight
            assert "bq" in r.pending_cancel  # cancel rides the next RPC
            assert not i0.failed
        finally:
            fleet.close()

    def test_interactive_shed_only_without_any_batch(self, tmp_path):
        from paddle_tpu.inference.fleet import FleetOverloaded
        fleet = _stub_fleet(tmp_path, "nobatch", max_pending=1)
        try:
            fleet.submit([1, 1], 4, request_id="i0")
            with pytest.raises(FleetOverloaded):
                fleet.submit([1, 2], 4, request_id="i1")
            st = fleet.stats()
            assert st["sheds_interactive"] == 1
            assert st["sheds_batch"] == 0
        finally:
            fleet.close()

    def test_priority_validated(self, tmp_path):
        fleet = _stub_fleet(tmp_path, "val")
        try:
            with pytest.raises(ValueError, match="priority"):
                fleet.submit([1], 4, priority="premium")
        finally:
            fleet.close()


# -------------------------------------------------- elastic lifecycle ----

def _live_worker_procs(fleet):
    n = 0
    with fleet._lock:
        reps = list(fleet._replicas)
    for r in reps:
        if r.worker is not None and r.worker["proc"].poll() is None:
            n += 1
    return n


class TestElasticFleet:
    @pytest.mark.slow      # ~40s subprocess e2e; tier-1 budget
    def test_scale_down_drains_then_stops_zero_lost(self, tmp_path):
        """ISSUE 11 satellite: scale 3 -> 1 while submit() traffic is
        live.  Zero lost, token-exact parity vs an in-process reference,
        and replicas_up telemetry matches the live process table at
        every transition."""
        import threading

        import jax
        from paddle_tpu.models import gpt as G
        cfg = G.GPTConfig(**TINY_CFG)
        params = G.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(5)
        prompts = [(rng.randint(1, 256, int(rng.randint(3, 8))), 32)
                   for _ in range(18)]
        ref = {f"r{i}": [int(t) for t in np.asarray(
            G.generate(params, cfg, np.asarray(p)[None], m))[0, len(p):]]
            for i, (p, m) in enumerate(prompts)}

        fleet = _fleet(tmp_path, "elastic", replicas=3)
        try:
            assert fleet.await_healthy(timeout=180) == 3
            assert _live_worker_procs(fleet) == 3 == fleet.replicas_up()

            def feed():
                for i, (p, m) in enumerate(prompts):
                    fleet.submit(p, m, request_id=f"r{i}",
                                 priority="batch" if i % 3 == 2
                                 else "interactive")
                    time.sleep(0.03)
            feeder = threading.Thread(target=feed)
            feeder.start()
            # scale 3 -> 2 -> 1 mid-stream, drain-then-stop each time
            for expect in (2, 1):
                rid = max(r.id for r in fleet._replicas)
                removed = fleet._replica_by_id(rid)
                fleet.remove_replica(rid, wait=True)
                assert fleet.nreplicas == expect
                # the removed worker's process is really gone, and the
                # telemetry agrees with the live process table
                assert removed.worker["proc"].poll() is not None
                assert fleet.replicas_up() == expect \
                    == _live_worker_procs(fleet)
            feeder.join(timeout=30)
            done, failed = fleet.drain(timeout=180)
            assert not failed and len(done) == 18, (len(done), failed)
            for rid_, want in ref.items():
                assert done[rid_].tokens == want, rid_
            st = fleet.stats()
            assert st["scale_downs"] == 2
            downs = [e for e in st["scale_events"]
                     if e["action"] == "scale_down"]
            assert len(downs) == 2
            assert all("done_t" in e for e in downs)
        finally:
            fleet.close()

    def test_add_replica_joins_warm_and_serves(self, tmp_path):
        """Scale-up hello rides the shared persistent cache: 0 compiles
        (warm_cache_misses == 0 on the scale event)."""
        cache = str(tmp_path / "jit_cache")
        fleet = _fleet(tmp_path, "addwarm", replicas=1,
                       jit_cache_dir=cache)
        try:
            assert fleet.await_healthy(timeout=180) == 1
            rng = np.random.RandomState(0)
            for i in range(3):      # fill the persistent cache
                fleet.submit(rng.randint(1, 256, 5), 8,
                             request_id=f"w{i}")
            done, failed = fleet.drain(timeout=120)
            assert not failed and len(done) == 3
            rid = fleet.add_replica()
            assert fleet.await_healthy(2, timeout=180) == 2
            ev = [e for e in fleet.scale_events
                  if e["action"] == "scale_up" and e["replica"] == rid]
            assert ev and ev[0]["warm_cache_misses"] == 0, ev
            for i in range(6):      # both replicas serve
                fleet.submit(rng.randint(1, 256, 5), 8,
                             request_id=f"x{i}")
            done, failed = fleet.drain(timeout=120)
            assert not failed and len(done) == 9
            assert fleet.stats()["scale_ups"] == 1
        finally:
            fleet.close()

    def test_autoscaler_survives_slow_start_and_scaleup_kill(self, tmp_path):
        """Chaos composition (ISSUE 11 tentpole): the scale-up replica
        is deterministically slow to hello AND gets SIGKILLed while
        starting.  The control loop must neither wedge nor lose work —
        every admitted request still completes."""
        from paddle_tpu.inference.autoscale import Autoscaler
        fleet = _fleet(
            tmp_path, "chaos_up", replicas=1, max_pending=64,
            fault_spec="replica_slow_start:seconds=2,rank=1,restart=0")
        scaler = None
        try:
            assert fleet.await_healthy(timeout=180) == 1
            scaler = Autoscaler(fleet, slo_p99_s=30.0, min_replicas=1,
                                max_replicas=2, cooldown_s=0.5,
                                interval_s=0.05, down_ticks=10 ** 6,
                                up_backlog_per_replica=0.5).start()
            rng = np.random.RandomState(9)
            for i in range(24):
                fleet.submit(rng.randint(1, 256, 5), 32,
                             request_id=f"r{i}")
            # the backlog forces a scale-up; its worker is slow-starting
            deadline = time.time() + 30
            new = None
            while new is None and time.time() < deadline:
                with fleet._lock:
                    new = next((r for r in fleet._replicas if r.id >= 1
                                and r.pid is not None), None)
                time.sleep(0.01)
            assert new is not None, "autoscaler never scaled up"
            # SIGKILL it mid-scale-up (it is still in its slow hello)
            fleet.kill_replica(new.id)
            done, failed = fleet.drain(timeout=180)
            assert not failed and len(done) == 24, (len(done), failed)
            st = fleet.stats()
            assert st["scale_ups"] >= 1
            # the loop is still ticking AFTER the chaos — not wedged
            t1 = scaler.stats()["ticks"]
            time.sleep(0.5)
            assert scaler.stats()["ticks"] > t1
            # the killed scale-up relaunches (restart=0 scoped the slow
            # start to the first incarnation) and joins eventually
            assert fleet.await_healthy(2, timeout=120) == 2
        finally:
            if scaler is not None:
                scaler.stop()
            fleet.close()
