"""Router crash-restart recovery (ISSUE 18 tentpole): rebuilt requests
keep their original deadlines, the autoscaler holds quiescently across
a router generation swap, and ONE in-process crash-then-resume e2e —
``fleet._crash()`` (the SIGKILL simulation: connections dropped,
journal abandoned un-fsynced, workers told nothing), then a second
``ServingFleet`` on the same journal dir that re-adopts the SAME
worker process and drains everything with zero lost requests.

The subprocess SIGKILL variant (supervised router, real signal 9) runs
in bench.py's routerchaos phase / tools/routerchaos_smoke.sh — this
file keeps tier-1 to one worker boot.
"""
import os
import time

import numpy as np
import pytest

from paddle_tpu.inference import journal as J
from paddle_tpu.inference.autoscale import Autoscaler
from paddle_tpu.inference.fleet import rebuild_request
from paddle_tpu.testing import faults
from paddle_tpu.testing.env import clean_cpu_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CFG = {"vocab_size": 256, "hidden_size": 32, "num_layers": 2,
            "num_heads": 2, "max_seq_len": 128, "dtype": "float32",
            "use_flash": False, "remat": False}
SPEC = {"cfg": TINY_CFG, "seed": 0, "slots": 2, "max_len": 96,
        "seq_buckets": [8], "batch_buckets": [1, 2]}


def _fleet(tmp_path, tag, replicas=1, fault_spec=None, **kw):
    from paddle_tpu.inference.fleet import ServingFleet
    env = clean_cpu_env(REPO, device_count=1)
    env.pop("PADDLE_FAULTS", None)
    if fault_spec:
        env["PADDLE_FAULTS"] = fault_spec
    kw.setdefault("heartbeat_s", 20)
    kw.setdefault("restart_backoff_s", 0.2)
    return ServingFleet(SPEC, replicas=replicas, env_base=env,
                        log_dir=str(tmp_path / tag / "logs"), **kw)


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


def _admit(rid, *, deadline_s=None, admit_wall=None, prompt=(1, 2, 3)):
    return {"t": "admit", "id": rid, "prompt": list(prompt),
            "max_new_tokens": 4, "eos_token": None,
            "deadline_s": deadline_s, "priority": "interactive",
            "phase": None,
            "admit_wall": time.time() if admit_wall is None
            else admit_wall}


# ----------------------------------------------- rebuilt requests ----

class TestRebuildRequest:
    def test_deadline_budget_survives_as_burned_time(self):
        view = {"id": "a", "rec": _admit("a", deadline_s=10.0,
                                         admit_wall=time.time() - 3.0),
                "status": "pending", "phase": None}
        req = rebuild_request(view)
        # 3s burned before the crash: submit_t sits ~3s in the past
        age = time.perf_counter() - req.submit_t
        assert 2.5 < age < 4.0
        assert req.deadline_s == 10.0
        assert not req.expired()
        # and an already-blown deadline reads as expired immediately
        stale = {"id": "b", "rec": _admit("b", deadline_s=2.0,
                                          admit_wall=time.time() - 60),
                 "status": "pending", "phase": None}
        assert rebuild_request(stale).expired()

    def test_decode_phase_keeps_stamp_drops_bytes(self):
        view = {"id": "c", "rec": _admit("c"), "status": "pending",
                "phase": "decode", "first_token": 7,
                "prefill_replica": 0, "retries": 1}
        req = rebuild_request(view)
        assert req.phase == "decode" and req.first_token == 7
        assert req.prefill_replica == 0 and req.retries == 1
        assert req.kv is None and req.kv_bytes == 0


# ------------------------------------- autoscaler quiescence law ----

class _SwapFleet:
    """autoscale_signals raises ONCE (the generation swap), then
    reports one recovering tick, then normal quiet signals."""

    def __init__(self):
        self.calls = 0

    def autoscale_signals(self, window_s, role=None):
        self.calls += 1
        if self.calls == 1:
            raise RuntimeError("fleet torn down under the tick")
        base = {"role": role, "backlog": 0, "pending": 0,
                "pending_fraction": 0.0, "configured": 1, "healthy": 1,
                "occupancy": 0.0, "p99_s": None, "p50_s": None,
                "window_n": 0, "sheds": 0,
                "accepted_tokens_per_step": 0.0, "spill_pressure": 0.0}
        base["recovering"] = self.calls == 2
        return base


class TestAutoscalerQuiescence:
    def test_generation_swap_holds_quiescently(self):
        a = Autoscaler(_SwapFleet(), min_replicas=1, max_replicas=4,
                       up_ticks=1)
        a._up_streak = 1                       # a stale pre-swap streak
        assert a.tick() is None                # raise -> quiescent hold
        assert a._counts["ticks_quiescent"] == 1
        assert a._counts["tick_errors"] == 0   # NOT a control-law error
        assert a._up_streak == 0               # streaks reset
        assert a.tick() is None                # recovering -> hold too
        assert a._counts["ticks_quiescent"] == 2
        assert a._counts["tick_errors"] == 0
        # the loop is alive: the next tick reads normal signals
        assert a.tick() is None
        assert a._counts["ticks_quiescent"] == 2
        assert a._counts["ticks"] == 3


# --------------------------------------- crash-resume e2e (1 boot) ----

class TestCrashResume:
    def test_crash_resume_readopts_worker_and_keeps_deadlines(
            self, tmp_path):
        """The whole tentpole in one worker boot: gen-1 journaled fleet
        completes a request and crashes SIGKILL-style; two more admits
        land in the journal (one with a long-blown deadline); gen-2 on
        the same dir re-adopts the SAME worker process (pid unchanged,
        no respawn), re-queues the journaled backlog, fails the expired
        request NAMED, serves the fresh one, and still answers polls
        for the pre-crash result."""
        jd = str(tmp_path / "wal")
        f1 = _fleet(tmp_path, "gen1", journal_dir=jd)
        pre = f1.submit(np.arange(1, 6, dtype=np.int32), 4,
                        request_id="pre-crash")
        done1, failed1 = f1.drain(timeout=180)
        assert not failed1 and "pre-crash" in done1
        pids_before = f1.replica_pids()
        assert list(pids_before.values()) != [None]
        f1._crash()

        # requests the dead router admitted but never served: appended
        # to the same journal the way its own admit records land
        w = J.JournalWriter(jd)
        w.append(_admit("expired", deadline_s=2.0,
                        admit_wall=time.time() - 60.0))
        w.append(_admit("fresh", prompt=(2, 3, 4, 5, 6)))
        w.close()

        f2 = _fleet(tmp_path, "gen2", journal_dir=jd)
        try:
            done2, failed2 = f2.drain(timeout=180)
            # zero lost: every journaled id resolved, by NAME
            assert failed2.keys() == {"expired"}
            assert failed2["expired"].error == "deadline_exceeded"
            assert "fresh" in done2
            assert len(done2["fresh"].tokens) == 4
            # the pre-crash RESULT survived the crash (poll dedupe)
            assert done2["pre-crash"].tokens == pre.tokens
            # warm re-adoption: same worker process, no respawn
            assert f2.replica_pids() == pids_before
            st = f2.stats()
            assert st["readopts"] == 1
            assert st["replica_restarts"] == 0
            assert st["recovery_requeues"] == 2
            assert st["router_recoveries"] == 1
            assert f2.router_recovery_s is not None
            assert f2.router_recovery_s >= 0
            assert not st["recovering"]
        finally:
            f2.close()
            f1.close()     # reaps the (now-dead) child's zombie
        # clean shutdown compacted the journal to a checkpoint: no
        # live requests left behind, finished statuses preserved for
        # a later generation's poll dedupe
        st3 = J.replay(jd)
        assert st3.live_requests() == []
        assert st3.requests["fresh"]["status"] == "done"
        assert st3.requests["expired"]["status"] == "failed"


# -------------------------------------- bounded dedupe footprint ----

class TestDoneRetention:
    def test_evict_keeps_newest_within_retention(self):
        """The _done/_failed tables (and with them every journal
        checkpoint) stay inside PADDLE_FLEET_DONE_RETENTION — oldest
        ids evicted first, insertion order."""
        from paddle_tpu.inference.fleet import ServingFleet

        class _Cfg:
            done_retention = 3

        table = {f"r{i}": i for i in range(10)}
        ServingFleet._evict_locked(_Cfg(), table)
        assert list(table) == ["r7", "r8", "r9"]


# ------------------------------- chaos faults (subprocess, slow) ----

@pytest.mark.slow
class TestRouterKillFault:
    def test_event_deterministic_router_kill_recovers(self, tmp_path):
        """router_kill:event=K — the SUPERVISED router SIGKILLs itself
        right after its K-th journal append.  The supervisor relaunches
        it against the same journal; every admitted request completes;
        the client rides through the death."""
        import json
        import socket as _socket
        import threading

        from paddle_tpu.inference.fleet_supervisor import (
            FleetClient, supervise_router)

        spec = dict(SPEC)
        probe = _socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        env = clean_cpu_env(REPO, device_count=1)
        env.pop("PADDLE_FAULTS", None)
        renv = dict(env)
        renv.update(
            PADDLE_FLEET_MODEL=json.dumps(spec),
            PADDLE_FLEET_CONTROL_PORT=str(port),
            PADDLE_FLEET_JOURNAL_DIR=str(tmp_path / "wal"),
            PADDLE_FLEET_LOG_DIR=str(tmp_path / "logs"),
            PADDLE_FLEET_HEARTBEAT_S="30",
            # fires once, in generation 0 only (restart=0): the
            # relaunched router appends the same records again and must
            # NOT re-die
            PADDLE_FAULTS="router_kill:event=6,restart=0")
        stop = threading.Event()
        out = {}

        def sup():
            try:
                out["incidents"] = supervise_router(
                    renv, backoff=0.2, log_dir=str(tmp_path),
                    stop_event=stop)
            except Exception as e:                         # noqa: BLE001
                out["error"] = f"{type(e).__name__}: {e}"

        th = threading.Thread(target=sup, daemon=True)
        th.start()
        client = FleetClient(port, retry_window_s=120.0)
        try:
            reqs = [{"id": f"k{i}", "prompt": [1 + i, 2, 3],
                     "max_new_tokens": 4} for i in range(4)]
            resp = client.submit(reqs)
            assert not resp["rejected"], resp
            deadline = time.time() + 150
            p = None
            while time.time() < deadline:
                p = client.poll()
                if p["pending"] == 0 \
                        and len(p["done"]) + len(p["failed"]) >= 4:
                    break
                time.sleep(0.05)
            assert p is not None and p["pending"] == 0
            assert not p["failed"], p["failed"]
            assert len(p["done"]) == 4
        finally:
            client.shutdown()
            stop.set()
            th.join(timeout=30)
        assert "error" not in out, out
        # the fault killed generation 0 exactly once
        assert len(out["incidents"]) == 1
        assert out["incidents"][0]["role"] == "router"


@pytest.mark.slow
class TestReadoptTimeout:
    def test_refused_readopt_expires_window_and_respawns(
            self, tmp_path, monkeypatch):
        """The readopt_timeout fault: the worker refuses to reconnect
        after the crash (exits instead).  The resumed router's recovery
        window must expire — incident, fresh spawn, journaled backlog
        re-served — zero lost, no wedge."""
        monkeypatch.setenv("PADDLE_FLEET_READOPT_TIMEOUT_S", "3")
        jd = str(tmp_path / "wal")
        f1 = _fleet(tmp_path, "gen1", journal_dir=jd,
                    fault_spec="readopt_timeout")
        f1.submit(np.arange(1, 6, dtype=np.int32), 4,
                  request_id="pre-crash")
        done1, failed1 = f1.drain(timeout=180)
        assert not failed1
        pid_before = list(f1.replica_pids().values())[0]
        f1._crash()

        w = J.JournalWriter(jd)
        w.append(_admit("queued", prompt=(2, 3, 4, 5)))
        w.close()

        f2 = _fleet(tmp_path, "gen2", journal_dir=jd)
        try:
            done2, failed2 = f2.drain(timeout=180)
            assert not failed2
            assert "queued" in done2
            assert len(done2["queued"].tokens) == 4
            st = f2.stats()
            # the worker never came back: a FRESH child served it
            assert st["readopts"] == 0
            assert st["replica_restarts"] >= 1
            assert st["router_recoveries"] == 1
            assert list(f2.replica_pids().values())[0] != pid_before
            assert f2.router_recovery_s is not None
        finally:
            f2.close()
            f1.close()
