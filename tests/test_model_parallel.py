"""Model-parallel subsystem (distributed/auto, ISSUE 10): sharding-rule
registry, 1F1B pipeline schedule, ZeRO-sharded optimizer states, and the
composed TP+PP+ZeRO train step — all on the 8-device virtual CPU mesh
from conftest.  Heavyweight full-model sweeps run in the slow tier."""
import os
import subprocess

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.jax_compat import partition_spec as P
from paddle_tpu.distributed import auto
from paddle_tpu.distributed.auto import (engine, pipeline, rules,
                                         zero as auto_zero)
from paddle_tpu.distributed.reducer import (Reducer, DeviceMeshAllReduce,
                                            MeshAxesAllReduce)
from paddle_tpu.models import gpt
from paddle_tpu.models.gpt_hybrid import NO_DECAY, LN_NAMES as LN
from paddle_tpu.optimizer.functional import adamw_update

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HY = dict(beta1=0.9, beta2=0.95, eps=1e-8, weight_decay=0.1,
          clip_norm=1.0)
LR = 1e-3


# --------------------------------------------------------------------------
# schedule / stage assignment
# --------------------------------------------------------------------------

def test_schedule_1f1b_table():
    s = pipeline.Schedule(n_microbatch=4, n_stages=2)
    assert s.n_ticks == 5
    # stage 0 forwards microbatch t at tick t; stage 1 lags one tick
    assert [row[0] for row in s.ticks] == [0, 1, 2, 3, None]
    assert [row[1] for row in s.ticks] == [None, 0, 1, 2, 3]
    assert s.bubble_fraction == pytest.approx(1 / 5)
    assert s.handoffs() == 5
    with pytest.raises(ValueError):
        pipeline.Schedule(0, 2)


def test_stage_assignment_ranges():
    a = pipeline.StageAssignment(8, 4)
    assert a.ranges == ((0, 2), (2, 4), (4, 6), (6, 8))
    assert a.stage_of_layer(5) == 2
    with pytest.raises(ValueError):          # uneven explicit ranges
        pipeline.StageAssignment(8, 2, ranges=[(0, 3), (3, 8)])
    with pytest.raises(ValueError):          # non-contiguous
        pipeline.StageAssignment(8, 2, ranges=[(0, 4), (5, 8)])
    with pytest.raises(ValueError):          # indivisible default
        pipeline.StageAssignment(7, 2)


def test_pipeline_microbatch_parity():
    """Pipelined stage runner == unpipelined apply to 1e-6 for every
    microbatch count (the microbatch schedule must not change math)."""
    mesh = engine.make_mesh(pp=2)
    rng = np.random.RandomState(0)
    # 4 stacked "layers": y = tanh(x @ w + b), 2 per stage
    W = jnp.asarray(rng.randn(4, 16, 16) * 0.3, jnp.float32)
    B = jnp.asarray(rng.randn(4, 16) * 0.1, jnp.float32)
    x = jnp.asarray(rng.randn(8, 16), jnp.float32)

    def stage_fn(stage_params, xx):
        w, b = stage_params

        def body(c, wb):
            return jnp.tanh(c @ wb[0] + wb[1]), None
        out, _ = jax.lax.scan(body, xx, (w, b))
        return out

    ref = stage_fn((W, B), x)
    for micro in (1, 2, 4, 8):
        run = pipeline.make_pipelined(mesh, stage_fn, n_microbatch=micro)
        got = run((W, B), x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-6)


# --------------------------------------------------------------------------
# rule registry
# --------------------------------------------------------------------------

def test_rules_registry_builtin_families():
    fams = rules.registered_families()
    assert {"gpt", "bert", "moe"} <= set(fams)
    cfg = gpt.gpt_tiny()
    specs = rules.rules_for("gpt", cfg)
    assert specs["blocks"]["qkv_w"] == P("pp", None, None, "tp")
    assert specs["blocks"]["proj_w"] == P("pp", "tp")
    with pytest.raises(KeyError):
        rules.rules_for("resnet9000")


def test_rules_prune_and_validate():
    cfg = gpt.gpt_tiny()
    specs = rules.rules_for("gpt", cfg)
    mesh_tp = engine.make_mesh(tp=2)         # pp sized 1
    pruned = rules.prune_to_mesh(specs, mesh_tp)
    assert pruned["blocks"]["qkv_w"] == P(None, None, None, "tp")
    assert pruned["blocks"]["ln1_g"] == P()
    shapes = jax.eval_shape(lambda k: gpt.init_params(cfg, k),
                            jax.random.PRNGKey(0))
    assert rules.validate(pruned, shapes, mesh_tp) == []
    # a spec that doesn't divide: vocab 512 over a 3-sized axis
    bad_mesh = engine.make_mesh(tp=2, dp=2)
    bad = dict(pruned)
    bad["wte"] = P(("tp", "dp"), None)       # 512 % 4 == 0 -> fine
    assert rules.validate(bad, shapes, bad_mesh) == []
    bad["wpe"] = P(None, ("tp", "dp"))       # 64 % 4 == 0 -> fine
    bad["lnf_g"] = P(("tp", "dp"))           # 64 % 4 == 0 -> fine
    bad["lnf_b"] = P("tp", "dp")             # rank-1 param, rank-2 spec
    viol = rules.validate(bad, shapes, bad_mesh)
    assert len(viol) == 1 and "lnf_b" in viol[0][0]


def test_register_rules_decorator():
    @rules.register_rules("_test_fam")
    def _rules(cfg):
        return {"w": P("tp")}
    assert rules.rules_for("_test_fam")["w"] == P("tp")
    del rules._REGISTRY["_test_fam"]


# --------------------------------------------------------------------------
# structured-axis ZeRO layout algebra
# --------------------------------------------------------------------------

def test_pick_zero_axis_and_specs():
    sizes = {"dp": 2, "tp": 2, "pp": 2}
    # free largest axis wins
    assert auto_zero.pick_zero_axis((128, 64), P(), sizes) == 0
    # tp-sharded axis can still take dp on the local extent
    assert auto_zero.pick_zero_axis((8, 64), P("tp"), sizes) in (0, 1)
    # no divisible axis -> None
    assert auto_zero.pick_zero_axis((3, 5), P(), sizes) is None
    # already dp-sharded -> None
    assert auto_zero.pick_zero_axis((8,), P("dp"), sizes) is None
    assert auto_zero.with_dp_axis(P("pp", None), 1) == P("pp", "dp")
    assert auto_zero.with_dp_axis(P("tp"), 0) == P(("tp", "dp"))

    mesh = engine.make_mesh(dp=2, tp=2, pp=2)
    cfg = gpt.gpt_tiny()
    specs = rules.prune_to_mesh(rules.rules_for("gpt", cfg), mesh)
    shapes = jax.eval_shape(lambda k: gpt.init_params(cfg, k),
                            jax.random.PRNGKey(0))
    mspecs, zaxes = auto_zero.zero_specs(specs, shapes, mesh,
                                         record=False)
    # every gpt_tiny leaf finds a dp axis on the 2x2x2 mesh
    assert all(z >= 0 for z in jax.tree_util.tree_leaves(zaxes))
    assert "dp" in rules.spec_axes(mspecs["blocks"]["qkv_w"])


def test_zero_fused_step_bit_parity():
    """ZeRO-sharded Adam (placement path, the donated fused step) must
    be BITWISE identical to the replicated fused step over 10 steps —
    placement moves bytes, never math."""
    mesh = engine.make_mesh(dp=8)

    def build():
        paddle.seed(7)
        return nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                             nn.Linear(32, 4))

    def run(stage):
        net = build()
        opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
        if stage:
            auto_zero.shard_optimizer_states(opt, mesh, stage=stage)
        rng = np.random.RandomState(0)
        for _ in range(10):
            x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
            y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
            loss = paddle.nn.functional.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return [np.asarray(p.numpy()) for p in net.parameters()], opt

    base, _ = run(0)
    shard, opt = run(1)
    for pa, pb in zip(base, shard):
        np.testing.assert_array_equal(pa, pb)
    # memory proof: moments live at ~1/dp per device
    per = auto_zero.optimizer_state_bytes(opt, per_device=True)
    full = auto_zero.optimizer_state_bytes(opt, per_device=False)
    assert per <= full / 8 + 64 * len(base)


def test_group_sharded_parallel_deprecated_alias():
    from paddle_tpu.distributed import sharding as legacy
    legacy._warned.discard("group_sharded_parallel")
    from paddle_tpu.parallel.mesh import mesh_scope
    mesh = engine.make_mesh(dp=8)
    paddle.seed(3)
    net = nn.Linear(16, 8)
    opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
    with mesh_scope(mesh):
        with pytest.warns(DeprecationWarning):
            net2, opt2, _ = legacy.group_sharded_parallel(net, opt,
                                                          level="os_g")
    assert net2 is net and opt2._zero_stage == 2
    assert getattr(opt2, "_accumulator_placement", None) is not None


# --------------------------------------------------------------------------
# per-axis reducer transport (ZeRO-2 grads through the overlap reducer)
# --------------------------------------------------------------------------

def _transport_net():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))


def _transport_run(mesh, transport, zero_stage=0, merge_every=1,
                   drop_head_grad=False):
    net = _transport_net()
    red = Reducer(net.parameters(), bucket_size_mb=0.001,
                  transport=transport, overlap=True,
                  fuse_into_step=True).install_hooks()
    opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
    if zero_stage:
        auto_zero.shard_optimizer_states(opt, mesh, stage=zero_stage)
    rng = np.random.RandomState(0)
    for i in range(6):
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
        if drop_head_grad:
            # exercise the grad-less-param zero-fill path: loss through
            # the first linear only
            h = net[0](x)
            loss = paddle.nn.functional.mse_loss(
                h, paddle.to_tensor(
                    rng.randn(8, 32).astype(np.float32)))
        else:
            loss = paddle.nn.functional.mse_loss(net(x), y)
        loss.backward()
        if (i + 1) % merge_every:
            continue                 # gradient merge: accumulate locally
        flats, layout, scale = red.pop_reduced()
        opt.step_from_buckets(flats, layout, scale)
        opt.clear_grad()
    red.remove_hooks()
    return [np.asarray(p.numpy()) for p in net.parameters()]


def test_mesh_axes_transport_parity_and_counters():
    mesh = engine.make_mesh(dp=2, tp=2)
    base = _transport_run(mesh, DeviceMeshAllReduce(mesh=mesh, axis="dp"))
    s0 = auto.sharding_stats()
    scat = _transport_run(mesh, MeshAxesAllReduce(mesh=mesh,
                                                  reduce_scatter=True),
                          zero_stage=2)
    s1 = auto.sharding_stats()
    psum = _transport_run(mesh, MeshAxesAllReduce(mesh=mesh,
                                                  reduce_scatter=False),
                          zero_stage=1)
    for pa, pb, pc in zip(base, scat, psum):
        # <=1-ulp: differently-partitioned XLA programs fuse the same
        # elementwise update slightly differently
        np.testing.assert_allclose(pa, pb, atol=5e-8)
        np.testing.assert_allclose(pa, pc, atol=5e-8)
    # one dp collective per bucket per step (2 buckets x 6 steps)
    assert s1["collectives_dp"] - s0["collectives_dp"] >= 12
    assert s1["bytes_dp"] > s0["bytes_dp"]


def test_mesh_axes_transport_gradient_merge():
    """Two accumulated backwards per step must equal one backward over
    the summed gradient (the reducer carries the TOTAL local grad)."""
    mesh = engine.make_mesh(dp=2, tp=2)
    merged = _transport_run(
        mesh, MeshAxesAllReduce(mesh=mesh, reduce_scatter=True),
        zero_stage=2, merge_every=2)
    merged2 = _transport_run(
        mesh, MeshAxesAllReduce(mesh=mesh, reduce_scatter=True),
        zero_stage=2, merge_every=2)
    for pa, pb in zip(merged, merged2):
        np.testing.assert_array_equal(pa, pb)   # deterministic
    assert any(not np.array_equal(a, b) for a, b in zip(
        merged, _transport_run(
            mesh, MeshAxesAllReduce(mesh=mesh, reduce_scatter=True),
            zero_stage=2, merge_every=1)))      # merge really changed it


def test_mesh_axes_transport_gradless_params():
    """Params outside the loss still ride the bucket as zeros (the
    deterministic-collective contract) without corrupting training."""
    mesh = engine.make_mesh(dp=2, tp=2)
    a = _transport_run(mesh,
                       MeshAxesAllReduce(mesh=mesh, reduce_scatter=True),
                       zero_stage=2, drop_head_grad=True)
    b = _transport_run(mesh,
                       DeviceMeshAllReduce(mesh=mesh, axis="dp"),
                       drop_head_grad=True)
    for pa, pb in zip(a, b):
        np.testing.assert_allclose(pa, pb, atol=5e-8)


def test_mesh_axes_transport_subset_mesh():
    """Transport over a SUBSET of the devices (a 2-device dp group out
    of 8) — the subset-group analogue on the single-process mesh."""
    sub = engine.make_mesh(dp=2, devices=jax.devices()[4:6])
    a = _transport_run(sub, MeshAxesAllReduce(mesh=sub), zero_stage=1)
    b = _transport_run(sub, DeviceMeshAllReduce(mesh=sub, axis="dp"))
    for pa, pb in zip(a, b):
        np.testing.assert_allclose(pa, pb, atol=5e-8)


# --------------------------------------------------------------------------
# composed engine: TP logit parity, full-step parity, memory
# --------------------------------------------------------------------------

def _reference_run(cfg, toks, labels, steps):
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    m = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)
    v = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)

    def step(params, m, v, t):
        loss, grads = jax.value_and_grad(
            lambda p: gpt.loss_fn(p, toks, labels, cfg))(params)
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                          for g in jax.tree_util.tree_leaves(grads)))
        scale = jnp.minimum(1.0, HY["clip_norm"] / jnp.maximum(gn, 1e-12))
        grads = jax.tree_util.tree_map(lambda g: g * scale, grads)

        def upd(path, p, g, mm, vv):
            leaf = str(getattr(path[-1], "key", path[-1]))
            decay = leaf not in NO_DECAY and leaf not in LN
            return adamw_update(p, g, mm, vv, LR, t, HY["beta1"],
                                HY["beta2"], HY["eps"],
                                HY["weight_decay"], decay)
        out = jax.tree_util.tree_map_with_path(upd, params, grads, m, v)
        tup = lambda o: isinstance(o, tuple) and len(o) == 3  # noqa: E731
        return (jax.tree_util.tree_map(lambda o: o[0], out, is_leaf=tup),
                jax.tree_util.tree_map(lambda o: o[1], out, is_leaf=tup),
                jax.tree_util.tree_map(lambda o: o[2], out, is_leaf=tup),
                loss)

    jstep = jax.jit(step)
    losses = []
    for t in range(1, steps + 1):
        params, m, v, loss = jstep(params, m, v, jnp.float32(t))
        losses.append(float(loss))
    return params, losses


def _mesh_run(cfg, mesh, toks, labels, steps, zero_stage, micro):
    params, m, v = auto.init_state(cfg, mesh, jax.random.PRNGKey(0),
                                   zero_stage=zero_stage)
    step = auto.make_train_step(cfg, mesh, n_microbatch=micro,
                                zero_stage=zero_stage, **HY)
    losses = []
    for t in range(1, steps + 1):
        params, m, v, loss = step(params, m, v, t, toks, labels, LR)
        losses.append(float(loss))
    return params, losses, step


def test_tp_logit_parity():
    """Compiler-partitioned TP forward == single-device logits (1e-5)."""
    cfg = gpt.gpt_tiny()
    mesh = engine.make_mesh(tp=4)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 32)), jnp.int32)
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    want = np.asarray(gpt.forward(params, toks, cfg))
    specs = rules.prune_to_mesh(rules.rules_for("gpt", cfg), mesh)
    placed = rules.place(params, mesh, specs)
    fwd = auto.make_forward(cfg, mesh)
    got = np.asarray(fwd(placed, toks))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.slow          # ~16s 8-device compose; tier-1 budget
def test_composed_step_parity_2x2x2():
    """The acceptance gate: dp=2,tp=2,pp=2 TP+PP+ZeRO-2 training matches
    the single-device run to 1e-5 per-step loss, and the per-device
    optimizer-state bytes shrink >= 1.9x at dp=2."""
    cfg = gpt.gpt_tiny()
    mesh = engine.make_mesh(dp=2, tp=2, pp=2)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 64)), jnp.int32)
    steps = 5
    auto.reset_sharding_stats()
    _, mesh_l, step = _mesh_run(cfg, mesh, toks, toks, steps, 2, 2)
    _, ref_l = _reference_run(cfg, toks, toks, steps)
    assert max(abs(a - b) for a, b in zip(mesh_l, ref_l)) <= 1e-5
    stats = auto.sharding_stats()
    assert stats["opt_state_shrink"] >= 1.9
    # plan-exact counters: one dp collective per leaf bucket per step
    assert stats["collectives_dp"] == step.plan.dp_collectives * steps
    assert stats["collectives_tp"] == step.plan.tp_collectives * steps
    assert stats["collectives_pp"] == step.plan.pp_collectives * steps
    assert stats["bubble_fraction_pct"] == pytest.approx(
        100 * step.schedule.bubble_fraction, abs=0.01)


def test_zero_stage1_vs_stage2_parity():
    """psum-then-slice (stage 1) and reduce-scatter (stage 2) are the
    same reduction — params must match closely after 3 steps."""
    cfg = gpt.gpt_tiny()
    mesh = engine.make_mesh(dp=2, tp=2, pp=2)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 64)), jnp.int32)
    p1, l1, _ = _mesh_run(cfg, mesh, toks, toks, 3, 1, 2)
    p2, l2, _ = _mesh_run(cfg, mesh, toks, toks, 3, 2, 2)
    assert l1 == pytest.approx(l2, abs=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


def test_make_mesh_validation():
    with pytest.raises(ValueError):
        engine.make_mesh(dp=4, tp=4)         # 16 > 8 devices
    mesh = engine.make_mesh(dp=2, tp=2, pp=2)
    assert engine.mesh_axis_sizes(mesh) == {
        "dp": 2, "pp": 2, "tp": 2, "sp": 1}


# --------------------------------------------------------------------------
# CI guard: the standing jax_compat constraint
# --------------------------------------------------------------------------

def test_shard_map_guard_clean():
    out = subprocess.run(
        [os.path.join(_REPO, "tools", "shard_map_guard.sh")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr


def test_shard_map_guard_catches_violation(tmp_path):
    bad = os.path.join(_REPO, "paddle_tpu", "_guard_violation_tmp.py")
    with open(bad, "w") as f:
        f.write("from jax.experimental.shard_map import shard_map\n")
    try:
        out = subprocess.run(
            [os.path.join(_REPO, "tools", "shard_map_guard.sh")],
            capture_output=True, text=True)
        assert out.returncode == 1
        assert "_guard_violation_tmp" in out.stderr
    finally:
        os.remove(bad)


# --------------------------------------------------------------------------
# slow tier: heavyweight sweeps
# --------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("dims", [(8, 1, 1, 2, 1), (1, 4, 2, 2, 4),
                                  (2, 2, 2, 1, 4)])
def test_engine_mesh_slice_sweep(dims):
    """Every mesh slice (pure dp / tp×pp / full hybrid) matches the
    single-device reference to 1e-5 over 5 steps."""
    dp, tp, pp, zs, micro = dims
    cfg = gpt.gpt_tiny()
    mesh = engine.make_mesh(dp=dp, tp=tp, pp=pp)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 64)), jnp.int32)
    _, mesh_l, _ = _mesh_run(cfg, mesh, toks, toks, 5, zs, micro)
    _, ref_l = _reference_run(cfg, toks, toks, 5)
    assert max(abs(a - b) for a, b in zip(mesh_l, ref_l)) <= 1e-5


@pytest.mark.slow
def test_engine_over_budget_config_trains():
    """A config whose replicated params+moments exceed the simulated
    per-device budget trains on the mesh with per-device bytes inside
    the budget (the bench.py --model-parallel scale phase, in-proc)."""
    cfg = gpt.GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                        num_heads=8, max_seq_len=128, dtype="float32",
                        use_flash=False, remat=False)
    budget = 8 * (1 << 20)
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(jax.eval_shape(
                       lambda k: gpt.init_params(cfg, k),
                       jax.random.PRNGKey(0))))
    assert n_params * 4 * 3 > budget
    mesh = engine.make_mesh(dp=2, tp=2, pp=2)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 64)), jnp.int32)
    auto.reset_sharding_stats()
    _, losses, _ = _mesh_run(cfg, mesh, toks, toks, 5, 2, 2)
    assert losses[-1] < losses[0] and all(np.isfinite(losses))
    stats = auto.sharding_stats()
    assert (stats["param_bytes_per_device"]
            + stats["opt_state_bytes_per_device"]) <= budget
