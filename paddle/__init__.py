"""Drop-in ``paddle`` alias for paddle_tpu.

Reference-era scripts start with ``import paddle`` / ``import
paddle.fluid as fluid`` — this shim makes those statements resolve to
paddle_tpu with ZERO edits: after import, ``paddle`` IS the paddle_tpu
module (sys.modules alias, so module identity, isinstance checks and
monkey-patches all agree), and every ``paddle.X[.Y]`` submodule import
aliases the matching ``paddle_tpu.X[.Y]`` module — eagerly for the tree
paddle_tpu already imported, lazily via a meta-path finder for anything
else — never a second module instance (duplicate registries would
corrupt the static-graph and autograd state).
"""
import importlib
import importlib.abc
import importlib.util
import sys

import paddle_tpu as _pt


class _AliasLoader(importlib.abc.Loader):
    """Loader that 'creates' the already-imported paddle_tpu module."""

    def __init__(self, real):
        self._real = real
        self._orig_spec = None

    def create_module(self, spec):
        mod = importlib.import_module(self._real)
        # module_from_spec will overwrite the REAL module's __spec__ with
        # the alias spec; remember the original so identity stays clean
        self._orig_spec = getattr(mod, "__spec__", None)
        return mod

    def exec_module(self, module):
        if self._orig_spec is not None:
            module.__spec__ = self._orig_spec
        if not hasattr(module, "__path__"):
            # package-like so `import paddle.x.y` consults the finders
            # for pseudo-submodules (attribute-only children like
            # fluid.contrib.layers) instead of refusing at the parent
            module.__path__ = []

    # runpy (``python -m paddle.distributed.launch``) requires the loader
    # to expose the module's code object — delegate to the real loader
    def get_code(self, fullname):
        spec = importlib.util.find_spec(self._real)
        if spec is not None and spec.loader is not None:
            return spec.loader.get_code(self._real)
        return None

    def get_source(self, fullname):
        spec = importlib.util.find_spec(self._real)
        if spec is not None and spec.loader is not None:
            return spec.loader.get_source(self._real)
        return None

    def is_package(self, fullname):
        spec = importlib.util.find_spec(self._real)
        return bool(spec is not None and spec.submodule_search_locations)


class _NamespaceLoader(importlib.abc.Loader):
    """Materialize an attribute-only pseudo-submodule (a SimpleNamespace
    or plain object on the parent module — e.g. fluid.contrib.layers,
    fluid.dygraph.base) as an importable module."""

    def __init__(self, obj):
        self._obj = obj

    def create_module(self, spec):
        import types
        if isinstance(self._obj, types.ModuleType):
            return self._obj
        mod = types.ModuleType(spec.name)
        src = self._obj
        ns = vars(src) if hasattr(src, "__dict__") else {
            k: getattr(src, k) for k in dir(src) if not k.startswith("_")}
        mod.__dict__.update(ns)
        return mod

    def exec_module(self, module):
        pass


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith("paddle."):
            return None
        real = "paddle_tpu." + fullname[len("paddle."):]
        try:
            if importlib.util.find_spec(real) is not None:
                return importlib.util.spec_from_loader(
                    fullname, _AliasLoader(real))
        except (ImportError, ValueError):
            pass
        # pseudo-submodule: an attribute of the parent real module
        parent, _, tail = real.rpartition(".")
        if not parent:
            return None
        try:
            pmod = importlib.import_module(parent)
        except ImportError:
            return None
        obj = getattr(pmod, tail, None)
        import types as _types
        from types import SimpleNamespace as _SNS
        # ONLY module-shaped attributes materialize: importing a class
        # or function as a module would make the import system REPLACE
        # the real attribute on the shared parent with a junk module
        if not isinstance(obj, (_types.ModuleType, _SNS)):
            return None
        return importlib.util.spec_from_loader(fullname,
                                               _NamespaceLoader(obj))


# alias every already-imported paddle_tpu submodule, then the root itself:
# ``import paddle`` after this returns paddle_tpu (identity, not a copy)
for _name, _mod in list(sys.modules.items()):
    if _name == "paddle_tpu" or _name.startswith("paddle_tpu."):
        sys.modules["paddle" + _name[len("paddle_tpu"):]] = _mod
        if not hasattr(_mod, "__path__"):
            # package-like so pseudo-submodule imports (attribute-only
            # children like fluid.contrib.layers) reach the finder
            _mod.__path__ = []

if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())
