"""Wide&Deep CTR training over tp-sharded sparse embedding tables.

The reference serves these models from a parameter server; here the
embedding tables shard over the mesh 'tp' axis (the SparseCore-style
layout), the dense towers replicate, and the batch shards over 'dp'.

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/wide_deep_rec.py --steps 20
"""
import argparse

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.models import rec
from paddle_tpu.parallel.mesh import create_mesh


def main(steps=20, batch=256, dp=2, tp=4, model="wide_deep"):
    cfg = rec.RecConfig(vocab_size=10007, num_fields=8, dense_dim=4,
                        embed_dim=16, mlp_dims=(64, 32))
    mesh = create_mesh(dp=dp, tp=tp)
    params, m, v = rec.init_sharded(cfg, mesh, jax.random.PRNGKey(0),
                                    model=model)
    step = rec.make_train_step(cfg, mesh, model=model)

    rng = np.random.RandomState(0)
    w_true = rng.randn(cfg.num_fields)
    for t in range(1, steps + 1):
        ids = rng.randint(0, cfg.vocab_size, (batch, cfg.num_fields))
        dense = rng.randn(batch, cfg.dense_dim).astype(np.float32)
        # synthetic CTR: click prob from a hidden linear model over ids
        logit = (ids % 7 - 3) @ w_true / cfg.num_fields
        y = (rng.rand(batch) < 1 / (1 + np.exp(-logit))).astype(np.float32)
        params, m, v, loss = step(params, m, v, jnp.int32(t),
                                  jnp.asarray(ids, jnp.int32),
                                  jnp.asarray(dense), jnp.asarray(y),
                                  jnp.float32(1e-2))
        if t % 5 == 0:
            print(f"step {t} logloss {float(loss):.4f}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--model", default="wide_deep",
                    choices=["wide_deep", "deepfm"])
    args = ap.parse_args()
    main(steps=args.steps, dp=args.dp, tp=args.tp, model=args.model)
