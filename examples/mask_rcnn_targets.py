"""Mask R-CNN training targets, end to end.

The reference's two-stage target pipeline (ref fluid/layers/detection.py:
generate_proposal_labels :2596 -> generate_mask_labels :2748) on the
TPU-native stack: RPN proposals are sampled into fg/bg RoIs with box
targets (fixed-shape device op), then the fg RoIs get class-specific
M x M binary mask targets rasterized host-side with COCO RLE parity —
and a tiny mask head consumes them to show the shapes line up for the
loss.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/mask_rcnn_targets.py
"""
import numpy as np

import paddle
import paddle.nn.functional as F
from paddle.fluid import layers

B, G, N, K, M = 1, 2, 8, 3, 14      # images, gts, proposals, classes, res

# ground truth: two boxes with rectangle polygons (class 1 and 2)
gt_boxes = np.array([[[10, 10, 60, 60], [70, 20, 120, 90]]], "float32")
gt_classes = np.array([[1, 2]], "int64")
is_crowd = np.array([[0, 0]], "int64")
im_info = np.array([[128.0, 128.0, 1.0]], "float32")
rect = lambda x0, y0, x1, y1: [x0, y0, x1, y0, x1, y1, x0, y1]  # noqa: E731
gt_polys = [[[rect(10, 10, 60, 60)], [rect(70, 20, 120, 90)]]]

# noisy RPN proposals around the gts + background
rng = np.random.RandomState(0)
props = np.concatenate([
    gt_boxes[0] + rng.randn(2, 4) * 2.0,
    rng.rand(N - 2, 4) * 40 + np.array([0, 0, 20, 20]),
]).astype("float32")[None]

# stage 1: sample fg/bg RoIs + box-regression targets (device op)
rois, labels, btgt, bin_w, bout_w = layers.generate_proposal_labels(
    paddle.to_tensor(props), paddle.to_tensor(gt_classes),
    paddle.to_tensor(is_crowd), paddle.to_tensor(gt_boxes),
    paddle.to_tensor(im_info), batch_size_per_im=8, fg_fraction=0.5,
    fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=K)
rois_np = np.asarray(rois.numpy())[0]
labels_np = np.asarray(labels.numpy())[0]
n_fg = int((labels_np > 0).sum())
print(f"sampled RoIs: {rois_np.shape[0]} rows, {n_fg} foreground")

# stage 2: mask targets for the fg RoIs (host-side rasterizer)
mask_rois, roi_has_mask, mask_int32, lod = layers.generate_mask_labels(
    im_info=im_info, gt_classes=[gt_classes[0]], is_crowd=[is_crowd[0]],
    gt_segms=gt_polys, rois=[rois_np], labels_int32=[labels_np],
    num_classes=K, resolution=M)
print(f"mask targets: {mask_int32.shape} (P x K*M*M), lod={lod.tolist()}")
assert mask_rois.shape[0] == n_fg

# a tiny mask head consuming the targets: per-class M x M logits
P = mask_rois.shape[0]
feat = paddle.to_tensor(rng.randn(P, 16).astype("float32"))
head = paddle.nn.Linear(16, K * M * M)
logits = head(feat)
targets = paddle.to_tensor(mask_int32.astype("float32"))
valid = paddle.to_tensor((mask_int32 >= 0).astype("float32"))
loss = (F.binary_cross_entropy_with_logits(
    logits, paddle.clip(targets, 0.0, 1.0), reduction="none")
    * valid).sum() / valid.sum()
print(f"mask head loss over {int(np.asarray(valid.numpy()).sum())} "
      f"supervised cells: {float(loss.numpy()):.4f}")
assert np.isfinite(float(loss.numpy()))

# sanity: each fg target's own-class slice has real mask pixels
m = mask_int32.reshape(P, K, M, M)
for p in range(P):
    own = [c for c in range(1, K)
           if not (m[p, c] == -1).all()]
    assert len(own) == 1, "exactly one supervised class slice per fg roi"
    assert m[p, own[0]].sum() > 0, "mask has foreground pixels"
print("Mask R-CNN target pipeline on the TPU-native core: OK")
