"""Flagship GPT pretraining over a hybrid dp x pp x tp x sp mesh.

One shard_map'ed SPMD step: Megatron tensor parallel, GPipe pipeline over
'pp', ring-attention sequence parallel over 'sp', data parallel grad psum,
global-norm clip, fused AdamW — XLA schedules the ICI collectives.

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/gpt_pretrain_hybrid.py --dp 2 --pp 2 --tp 2 --steps 5
"""
import argparse

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt, gpt_hybrid
from paddle_tpu.parallel.mesh import create_mesh
from paddle_tpu.utils import CheckpointManager


def main(dp=2, pp=2, tp=2, sp=1, steps=5, batch=8, seq=128,
         ckpt_dir=None):
    cfg = gpt.GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                        num_heads=4, max_seq_len=seq, use_flash=False,
                        remat=True, dtype="float32")
    mesh = create_mesh(dp=dp, tp=tp, pp=pp, sp=sp)
    print(f"mesh dp={dp} pp={pp} tp={tp} sp={sp}; "
          f"model {cfg.num_params()/1e6:.1f}M params")

    params, m, v = gpt_hybrid.init_sharded(cfg, mesh, jax.random.PRNGKey(0))
    step = gpt_hybrid.make_train_step(cfg, mesh, n_microbatch=2)

    rng = np.random.RandomState(0)
    for t in range(1, steps + 1):
        toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                           jnp.int32)
        params, m, v, loss = step(params, m, v, jnp.int32(t), toks, toks,
                                  jnp.float32(3e-4))
        print(f"step {t} loss {float(loss):.4f}")

    if ckpt_dir:
        import pickle
        with open(f"{ckpt_dir}/gpt_final.pkl", "wb") as f:
            pickle.dump(jax.tree.map(np.asarray, params), f)
        print(f"saved to {ckpt_dir}/gpt_final.pkl")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--ckpt-dir", default=None)
    args = ap.parse_args()
    main(dp=args.dp, pp=args.pp, tp=args.tp, sp=args.sp, steps=args.steps,
         ckpt_dir=args.ckpt_dir)
