"""ResNet50 static-graph data-parallel training (BASELINE.json configs[1]).

The reference's recipe (ref: fluid/parallel_executor.cc + the ResNet50
fleet benchmark) replicates the program per GPU and NCCL-all-reduces
gradients; here the SAME user program runs batch-sharded over every
available device through ParallelExecutor — GSPMD inserts the gradient
all-reduce inside the jitted train step.

Run (8 virtual devices):
  PYTHONPATH=. JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/resnet50_static_dp.py --steps 3 --batch 16 --image-size 64

Prints an imgs/sec line per step and one summary JSON line.
"""
import argparse
import json
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.vision.models import resnet50
import paddle_tpu.nn.functional as F


def build_program(image_size, num_classes=1000, lr=0.002):
    # lr 0.1 is the ImageNet-schedule reference value; this short
    # random-data demo needs a warmup-scale lr or momentum overshoots
    # within 10 steps (verified: 0.02 diverges, 0.002 descends)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = static.data("image", [None, 3, image_size, image_size],
                          "float32")
        label = static.data("label", [None, 1], "int64")
        net = resnet50(num_classes=num_classes)
        logits = net(img)
        loss = F.cross_entropy(logits, label).mean()
        opt = paddle.optimizer.Momentum(learning_rate=lr, momentum=0.9,
                                        weight_decay=1e-4)
        opt.minimize(loss)
    return main, startup, loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--classes", type=int, default=1000)
    args = ap.parse_args()

    paddle.enable_static()
    main_prog, startup, loss = build_program(args.image_size, args.classes)
    exe = static.ParallelExecutor(loss_name="loss", main_program=main_prog)
    exe.run(startup)

    rng = np.random.RandomState(0)
    imgs_per_sec = []
    first = last = None
    for step in range(args.steps):
        x = rng.randn(args.batch, 3, args.image_size,
                      args.image_size).astype(np.float32)
        y = rng.randint(0, args.classes, (args.batch, 1)).astype(np.int64)
        t0 = time.perf_counter()
        lv, = exe.run(feed={"image": x, "label": y}, fetch_list=[loss])
        dt = time.perf_counter() - t0
        lv = float(np.asarray(lv))
        if step > 0:           # step 0 pays the compile
            imgs_per_sec.append(args.batch / dt)
        first = lv if first is None else first
        last = lv
        print(f"step {step}: loss={lv:.4f} imgs/s={args.batch / dt:.1f}")
    paddle.disable_static()
    print(json.dumps({
        "metric": "resnet50_imgs_per_sec",
        "value": round(float(np.mean(imgs_per_sec)) if imgs_per_sec else 0,
                       1),
        "unit": "imgs/s",
        "first_loss": round(first, 4), "last_loss": round(last, 4)}))


if __name__ == "__main__":
    main()
