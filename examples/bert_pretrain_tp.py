"""ERNIE/BERT masked-LM pretraining on a dp x tp mesh (GSPMD Megatron).

The whole step — loss, backward, clip, fused AdamW — is one compiled SPMD
program; param_specs drive XLA to insert the tp allreduces and the dp grad
reduction (the reference reaches the same point via fleet's c_allreduce
graph rewrites).

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/bert_pretrain_tp.py --dp 2 --tp 4 --steps 10
On a TPU pod slice, drop the env vars and size --dp/--tp to the slice.
"""
import argparse

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.models import bert


def synth_batch(cfg, batch, rng):
    """Masked-LM batch: 15% of tokens masked as targets, rest ignored."""
    tokens = rng.randint(0, cfg.vocab_size, (batch, cfg.max_seq_len))
    mask = rng.rand(batch, cfg.max_seq_len) < 0.15
    labels = np.where(mask, tokens, -100)
    nsp = rng.randint(0, 2, (batch,))
    return (jnp.asarray(tokens, jnp.int32), jnp.asarray(labels, jnp.int32),
            jnp.asarray(nsp, jnp.int32))


def main(dp=2, tp=4, steps=10, batch=16, config="tiny"):
    cfg = {"tiny": bert.bert_tiny, "base": bert.bert_base,
           "ernie3": bert.ernie_3_base}[config]()
    devs = np.array(jax.devices()[:dp * tp]).reshape(dp, tp)
    mesh = Mesh(devs, ("dp", "tp"))
    print(f"mesh dp={dp} tp={tp} on {devs.size} x "
          f"{jax.devices()[0].platform}")

    rng = np.random.RandomState(0)
    with mesh:
        params, m, v = bert.init_pretrain_state(cfg, jax.random.PRNGKey(0),
                                                mesh)
        step = bert.make_train_step(cfg, mesh)
        for t in range(1, steps + 1):
            tokens, labels, nsp = synth_batch(cfg, batch, rng)
            params, m, v, loss = step(params, m, v, jnp.int32(t),
                                      tokens, labels, nsp,
                                      jnp.float32(1e-4))
            print(f"step {t} mlm+nsp loss {float(loss):.4f}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--config", default="tiny",
                    choices=["tiny", "base", "ernie3"])
    args = ap.parse_args()
    main(dp=args.dp, tp=args.tp, steps=args.steps, config=args.config)
