"""Tiny SSD-style detector end-to-end on synthetic shapes.

Exercises the detection family as one pipeline: multi_box_head priors +
conv heads -> ssd_loss training (IoU matching, hard-negative mining) ->
detection_output inference (box decode + multiclass NMS).  Synthetic task:
images contain one bright axis-aligned square; the gt box is its bounds.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/ssd_detection.py
(ref: fluid/layers/detection.py ssd_loss/multi_box_head/detection_output)
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import detection as D


def make_batch(rng, n=8, size=32):
    imgs = np.zeros((n, 1, size, size), np.float32)
    boxes = np.zeros((n, 1, 4), np.float32)
    for i in range(n):
        s = rng.randint(8, 16)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        imgs[i, 0, y0:y0 + s, x0:x0 + s] = 1.0
        boxes[i, 0] = [x0 / size, y0 / size, (x0 + s) / size,
                       (y0 + s) / size]
    labels = np.ones((n, 1), np.int64)      # class 1 = "square"
    return imgs, boxes, labels


class TinySSD(nn.Layer):
    def __init__(self, n_priors_per_cell):
        super().__init__()
        self.backbone = nn.Sequential(
            nn.Conv2D(1, 16, 3, stride=2, padding=1), nn.ReLU(),
            nn.Conv2D(16, 32, 3, stride=2, padding=1), nn.ReLU())
        self.loc = nn.Conv2D(32, n_priors_per_cell * 4, 3, padding=1)
        self.conf = nn.Conv2D(32, n_priors_per_cell * 2, 3, padding=1)

    def forward(self, x):
        f = self.backbone(x)                           # [B, 32, 8, 8]
        B = x.shape[0]
        loc = paddle.reshape(paddle.transpose(self.loc(f), [0, 2, 3, 1]),
                             [B, -1, 4])
        conf = paddle.reshape(paddle.transpose(self.conf(f), [0, 2, 3, 1]),
                              [B, -1, 2])
        return f, loc, conf


def main():
    rng = np.random.RandomState(0)
    model = TinySSD(n_priors_per_cell=3)
    opt = paddle.optimizer.Adam(2e-3, parameters=model.parameters())

    # priors for the single 8x8 feature map
    feat = paddle.zeros([1, 32, 8, 8])
    image = paddle.zeros([1, 1, 32, 32])
    priors, pvars = D.prior_box(feat, image, min_sizes=[10.0],
                                max_sizes=[20.0], aspect_ratios=[2.0],
                                flip=False, clip=True)
    priors_flat = paddle.reshape(priors, [-1, 4])

    first = last = None
    for step in range(60):
        imgs, boxes, labels = make_batch(rng)
        _, loc, conf = model(paddle.to_tensor(imgs))
        loss = D.ssd_loss(loc, conf, paddle.to_tensor(boxes),
                          paddle.to_tensor(labels), priors_flat,
                          overlap_threshold=0.4)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
        if step % 20 == 0:
            print(f"step {step}: ssd_loss={float(loss):.4f}")
    assert last < first * 0.7, (first, last)

    # inference: decode + NMS, check the top box overlaps the true square
    imgs, boxes, _ = make_batch(rng, n=2)
    _, loc, conf = model(paddle.to_tensor(imgs))
    from paddle_tpu.fluid.layers import detection_output
    det = detection_output(loc, F.softmax(conf, axis=-1), priors_flat,
                           paddle.to_tensor(
                               np.broadcast_to(
                                   np.asarray([0.1, 0.1, 0.2, 0.2],
                                              np.float32),
                                   (priors_flat.shape[0], 4)).copy()),
                           score_threshold=0.01, keep_top_k=5)
    d = det.numpy()
    print("top detection rows (label, score, x1, y1, x2, y2):")
    print(np.round(d[0, :2], 3))
    assert (d[:, 0, 0] >= 0).all(), "no detection survived NMS"
    print("SSD pipeline (priors -> ssd_loss -> detection_output): OK")


if __name__ == "__main__":
    main()
