"""Autoregressive text generation with the KV cache.

Run: python examples/gpt_generate.py --new 32 --temperature 0.8 --top-k 40
(random weights — token streams, not prose; swap in trained params via
paddle.load for real text)
"""
import argparse
import functools

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt


def main(new=32, temperature=0.0, top_k=0):
    cfg = gpt.GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                        num_heads=4, max_seq_len=256, use_flash=False,
                        remat=False, dtype="float32")
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16)),
        jnp.int32)

    gen = jax.jit(functools.partial(
        gpt.generate, cfg=cfg, max_new_tokens=new, temperature=temperature,
        top_k=top_k))
    out = gen(params, prompt=prompt, key=jax.random.PRNGKey(42))
    for i, row in enumerate(np.asarray(out)):
        print(f"seq {i}: prompt={row[:16].tolist()}")
        print(f"       gen={row[16:].tolist()}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    args = ap.parse_args()
    main(new=args.new, temperature=args.temperature, top_k=args.top_k)
