"""The classic parameter-server-era data pipeline, end to end.

DataGenerator script -> MultiSlot protocol -> InMemoryDataset ->
exe.train_from_dataset — the reference's PS trainer input path (ref
fleet/data_generator, fleet/dataset, fluid executor train_from_dataset)
running unmodified on the TPU-native core: the generator emits the exact
trainer-pipe text protocol, the dataset pipes raw files through it and
parses batches into fixed-shape arrays, and the Executor streams them
through one jitted step.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/ps_dataset_pipeline.py
"""
import os
import sys
import tempfile

import numpy as np

import paddle
import paddle.fluid as fluid
import paddle.distributed as dist

tmp = tempfile.mkdtemp()

# 1) the user's DataGenerator script (normally its own file, run by the
#    dataset's pipe_command exactly like the reference trainer does)
gen_script = os.path.join(tmp, "my_generator.py")
with open(gen_script, "w") as f:
    f.write("""
import sys
sys.path.insert(0, %r)
from paddle.distributed import fleet

class LinearData(fleet.MultiSlotDataGenerator):
    def generate_sample(self, line):
        def iterate():
            a, b, label = line.split()
            yield [("feat", [float(a), float(b)]),
                   ("label", [float(label)])]
        return iterate

LinearData().run_from_stdin()
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 2) raw training shards (y = 2a + 3b)
rng = np.random.RandomState(0)
raw = os.path.join(tmp, "part-00000")
with open(raw, "w") as f:
    for _ in range(256):
        a, b = rng.rand(2)
        f.write(f"{a:.5f} {b:.5f} {2 * a + 3 * b:.5f}\n")

paddle.enable_static()
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    feat = fluid.layers.data("feat", [2], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    pred = fluid.layers.fc(feat, 1)
    loss = fluid.layers.reduce_mean(fluid.layers.square(pred - label))
    fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)

    dataset = dist.InMemoryDataset()
    dataset.init(batch_size=16, use_var=[feat, label],
                 pipe_command=f"{sys.executable} {gen_script}")
    dataset.set_filelist([raw])
    dataset.load_into_memory()
    dataset.local_shuffle()
    print(f"loaded {dataset.get_memory_data_size()} samples")

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for epoch in range(8):
        exe.train_from_dataset(main, dataset, fetch_list=[loss],
                               fetch_info=["loss"], print_period=16)

    test = exe.run(main,
                   feed={"feat": np.array([[0.5, 0.5]], "float32"),
                         "label": np.array([[2.5]], "float32")},
                   fetch_list=[loss])
paddle.disable_static()
final = float(np.asarray(test[0]))
print(f"held-out squared error: {final:.2e}")
assert final < 1e-3
print("PS-era dataset pipeline on the TPU-native core: OK")
