"""Seq2seq with beam-search decoding: learn to REVERSE token sequences.

The fluid-era NMT recipe (ref: the reference's machine-translation line —
RNN encoder/decoder + beam search) on the TPU-native stack: nn.GRU
encoder, GRUCell decoder trained with teacher forcing, and
BeamSearchDecoder + dynamic_decode (gather_tree ancestry) for inference.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/seq2seq_reverse.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn

V = 12          # 0 pad/start, 1..9 payload, 10 start, 11 end
START, END = 10, 11
T = 5
H = 64


def make_batch(rng, n):
    src = rng.randint(1, 10, (n, T))
    tgt = src[:, ::-1].copy()
    dec_in = np.concatenate([np.full((n, 1), START), tgt[:, :-1]], 1)
    return src, dec_in, tgt


class Seq2Seq(nn.Layer):
    def __init__(self):
        super().__init__()
        self.src_emb = nn.Embedding(V, H)
        self.tgt_emb = nn.Embedding(V, H)
        self.encoder = nn.GRU(H, H)
        self.cell = nn.GRUCell(H, H)
        self.proj = nn.Linear(H, V)

    def encode(self, src):
        _, h = self.encoder(self.src_emb(src))
        return h[0]                                  # [B, H]

    def forward(self, src, dec_in):
        h = self.encode(src)
        emb = self.tgt_emb(dec_in)                   # [B, T, H]
        outs = []
        state = h
        for t in range(T):
            o, state = self.cell(emb[:, t], state)
            outs.append(self.proj(o))
        return paddle.stack(outs, axis=1)            # [B, T, V]


def main():
    rng = np.random.RandomState(0)
    model = Seq2Seq()
    opt = paddle.optimizer.Adam(2e-3, parameters=model.parameters())
    lossf = nn.CrossEntropyLoss()

    first = last = None
    for step in range(300):
        src, dec_in, tgt = make_batch(rng, 64)
        logits = model(paddle.to_tensor(src), paddle.to_tensor(dec_in))
        loss = lossf(paddle.reshape(logits, [-1, V]),
                     paddle.to_tensor(tgt.reshape(-1)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
        if step % 100 == 0:
            print(f"step {step}: loss={float(loss):.4f}")
    assert last < 0.2, (first, last)

    # beam-search inference through the SAME cell + projection
    class DecCell(nn.Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, tok_emb, state):
            o, s = self.m.cell(tok_emb, state)
            return self.m.proj(o), s

    src, _, tgt = make_batch(rng, 4)
    h = model.encode(paddle.to_tensor(src))
    dec = nn.BeamSearchDecoder(DecCell(model), start_token=START,
                               end_token=END, beam_size=3,
                               embedding_fn=model.tgt_emb)
    out, _ = nn.dynamic_decode(dec, inits=h, max_step_num=T)
    best = out.numpy()[:, :, 0]                      # [B, T] best beam
    acc = (best[:, :T] == tgt).mean()
    print("greedy-beam decode accuracy:", acc)
    print("sample src:", src[0], "-> decoded:", best[0], "(want",
          tgt[0], ")")
    assert acc > 0.9, acc
    print("seq2seq + beam search: OK")


if __name__ == "__main__":
    main()
