"""Classic fluid py_reader training loop, running unmodified.

The reference-era async input idiom (ref fluid/layers/io.py:561):
py_reader + decorate_paddle_reader + start()/EOFException/reset() —
demonstrating that the single most common fluid input pattern works
verbatim on the TPU-native core.  The prefetch thread stages batches
through the native C++ ring (double buffer analogue) when available.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/fluid_py_reader_mnist.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import fluid

paddle.enable_static()

main_prog, startup_prog = fluid.Program(), fluid.Program()
with fluid.program_guard(main_prog, startup_prog):
    reader = fluid.layers.py_reader(capacity=16,
                                    shapes=[(-1, 1, 28, 28), (-1, 1)],
                                    dtypes=["float32", "int64"])
    img, lbl = fluid.layers.read_file(reader)
    flat = fluid.layers.reshape(img, [-1, 784])
    h = fluid.layers.fc(flat, 200, activation="relu")
    logits = fluid.layers.fc(h, 10)
    loss, probs = fluid.layers.softmax_with_cross_entropy(
        logits, lbl, return_softmax=True)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(probs, lbl)

    opt = fluid.optimizer.AdamOptimizer(1e-3)
    opt.minimize(avg_loss)

    from paddle_tpu.vision.datasets import MNIST
    ds = MNIST(mode="train")

    def mnist_batches():
        def sample_reader():
            for i in range(512):
                x, y = ds[i]
                yield (np.asarray(x, "float32").reshape(784),
                       np.asarray(y, "int64").reshape(1))
        return sample_reader

    import paddle_tpu.reader as preader
    reader.decorate_paddle_reader(
        preader.batch(mnist_batches(), batch_size=64))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_prog)

    for epoch in range(3):
        reader.start()
        n = 0
        try:
            while True:
                lv, av = exe.run(main_prog, fetch_list=[avg_loss, acc])
                n += 1
        except fluid.core.EOFException:
            reader.reset()
        print(f"epoch {epoch}: {n} steps, "
              f"loss={float(lv):.4f} acc={float(av):.3f}")

paddle.disable_static()
assert float(lv) < 1.0, "py_reader training failed to converge"
print("fluid py_reader async input on the TPU-native core: OK")
