"""Fluid-era training script running unmodified on the TPU-native core.

This is deliberately written in the REFERENCE's old spelling —
fluid.layers.fc / fluid.optimizer.AdamOptimizer / exe.run(feed, fetch_list)
— to demonstrate that code written against lanxianghit/Paddle's primary API
works on paddle_tpu without edits (the whole program compiles through XLA
underneath; ref: python/paddle/fluid).

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/fluid_style_mnist.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import fluid

paddle.enable_static()

main_prog, startup_prog = fluid.Program(), fluid.Program()
with fluid.program_guard(main_prog, startup_prog):
    img = fluid.layers.data("img", [784])
    lbl = fluid.layers.data("label", [1], dtype="int64")
    h = fluid.layers.fc(img, 200, activation="relu")
    h = fluid.layers.fc(h, 200, activation="relu")
    logits = fluid.layers.fc(h, 10)
    loss, probs = fluid.layers.softmax_with_cross_entropy(
        logits, lbl, return_softmax=True)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(probs, lbl)

    opt = fluid.optimizer.AdamOptimizer(1e-3)
    opt.minimize(avg_loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_prog)

    # synthetic MNIST-like data (structured so it is learnable)
    from paddle_tpu.vision.datasets import MNIST
    ds = MNIST(mode="train")
    xs = np.stack([np.asarray(ds[i][0]).reshape(784) for i in range(512)])
    ys = np.stack([np.asarray(ds[i][1]).reshape(1) for i in range(512)])

    for epoch in range(3):
        for i in range(0, 512, 64):
            lv, av = exe.run(main_prog,
                             feed={"img": xs[i:i + 64],
                                   "label": ys[i:i + 64]},
                             fetch_list=[avg_loss, acc])
        print(f"epoch {epoch}: loss={float(lv):.4f} acc={float(av):.3f}")

paddle.disable_static()
assert float(lv) < 0.5, "fluid-style training failed to converge"
print("fluid-style static training on the TPU-native core: OK")
