"""LeNet on MNIST, dygraph style (the reference's hello-world train loop).

Run: python examples/mnist_lenet.py [--epochs 1]
"""
import argparse

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.transforms import Normalize


def main(epochs=1, batch_size=256, steps=None):
    # transforms see the RAW uint8 image (reference semantics), so the
    # classic fluid-era constants: (x - 127.5) / 127.5 -> [-1, 1]
    transform = Normalize(mean=[127.5], std=[127.5], data_format="CHW")
    train = MNIST(mode="train", transform=transform)
    test = MNIST(mode="test", transform=transform)

    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())

    loader = DataLoader(train, batch_size=batch_size, shuffle=True,
                        num_workers=2)
    for epoch in range(epochs):
        model.train()
        for step, (x, y) in enumerate(loader):
            loss = paddle.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if step % 20 == 0:
                print(f"epoch {epoch} step {step} "
                      f"loss {float(loss.numpy()):.4f}")
            if steps and step >= steps:
                break

    model.eval()
    correct = total = 0
    for x, y in DataLoader(test, batch_size=512):
        pred = model(x).argmax(-1)
        correct += int((pred == y.flatten()).sum().numpy())
        total += int(y.shape[0])
    print(f"test accuracy: {correct / total:.4f}")
    return correct / total


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--steps", type=int, default=None,
                    help="cap steps per epoch (smoke mode)")
    args = ap.parse_args()
    main(epochs=args.epochs, steps=args.steps)
