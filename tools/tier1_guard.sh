#!/usr/bin/env bash
# Tier-1 regression guard: run the tier-1 suite (ROADMAP.md's verify
# command), then FAIL if the run left the worktree dirty — tests must not
# litter artifacts into tracked paths (the PR-1 cleanup git-rm'd ~13MB of
# accidentally-committed test outputs; this keeps them from creeping back).
#
# Usage: tools/tier1_guard.sh [extra pytest args...]
# Exit:  pytest's status, or 1 if the suite passed but dirtied the tree.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

if ! git diff --quiet || ! git diff --cached --quiet \
        || [ -n "$(git status --porcelain)" ]; then
    echo "tier1_guard: worktree dirty BEFORE the run — commit or stash" \
         "first so post-run litter is attributable:" >&2
    git status --porcelain >&2
    exit 2
fi

# compile-hygiene lint runs first: a NEW static-analysis finding fails
# tier-1 the same way post-run litter does (and in seconds, not minutes)
if ! tools/lint_guard.sh; then
    echo "tier1_guard: FAIL — static analysis found new issues" \
         "(tools/lint_guard.sh; see above)" >&2
    exit 1
fi

# AOT cold-start smoke (~10s): serialize-executable round trip, zero
# compiles on the artifact-warm replica, token parity — the compile
# layer's end-to-end contract, cheap enough to gate every tier-1 run
if ! tools/aot_smoke.sh; then
    echo "tier1_guard: FAIL — AOT cold-start smoke" \
         "(tools/aot_smoke.sh; see above)" >&2
    exit 1
fi

# prefill/decode disaggregation smoke (~25s): 1 prefill + 1 decode
# replica, decode p99 flat under long-prompt pressure, KV pages handed
# off through the router, zero lost — the ISSUE-15 fleet contract
if ! tools/disagg_smoke.sh; then
    echo "tier1_guard: FAIL — disaggregation smoke" \
         "(tools/disagg_smoke.sh; see above)" >&2
    exit 1
fi

# fleet-scale KV smoke (~30s): 2 replicas + host tier vs 1 giant on
# shared-prefix traffic — sticky routing holds the hit-rate, pages
# spill and hash-verify back with zero re-prefills, zero steady-state
# compiles — the ISSUE-17 fleet contract
if ! tools/kvtier_smoke.sh; then
    echo "tier1_guard: FAIL — fleet-scale KV smoke" \
         "(tools/kvtier_smoke.sh; see above)" >&2
    exit 1
fi

# distributed-tracing smoke (~15s): traced 2-replica disagg fleet —
# lifecycles assemble causally ordered across router/prefill/decode
# with zero negative spans, and an injected router kill leaves a
# flight dump naming every in-flight request — the ISSUE-19 contract
if ! tools/trace_smoke.sh; then
    echo "tier1_guard: FAIL — distributed tracing smoke" \
         "(tools/trace_smoke.sh; see above)" >&2
    exit 1
fi

# router fault-tolerance smoke (~60s): SIGKILL the journaled router
# mid-traffic, relaunch against the same journal, re-adopt the
# surviving workers — zero lost, token-exact, zero replica restarts,
# zero re-adoption compiles — the ISSUE-18 control-plane contract
if ! tools/routerchaos_smoke.sh; then
    echo "tier1_guard: FAIL — router chaos smoke" \
         "(tools/routerchaos_smoke.sh; see above)" >&2
    exit 1
fi

# pipeline-stage serving smoke (~35s): a model too big for a whole
# tp=2 tier serves token-exact on the 2x2 pp x tp mesh, one decode
# executable across stages, zero steady-state compiles — the ISSUE-20
# tentpole contract
if ! tools/ppserve_smoke.sh; then
    echo "tier1_guard: FAIL — pipeline-stage serving smoke" \
         "(tools/ppserve_smoke.sh; see above)" >&2
    exit 1
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"

dirty=$(git status --porcelain)
if [ -n "$dirty" ]; then
    echo "tier1_guard: FAIL — the test run dirtied the worktree:" >&2
    echo "$dirty" >&2
    exit 1
fi
exit "$rc"
