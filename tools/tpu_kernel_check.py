"""On-chip Pallas kernel check: compile (no interpret) every kernel on the
real TPU, assert parity vs the XLA reference path, and time both.

Run:  python tools/tpu_kernel_check.py
Writes results to stdout and tools/tpu_kernel_check.json.

Timing note: in this environment ``block_until_ready`` does not synchronize
through the remote-execution layer, so every timed region ends with a host
fetch (``float(jnp.sum(...))``) — see VERDICT round 2.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

# Wall-clock budget for the block sweeps (the bench orchestrator runs this
# as a SIGKILL-bounded phase — a partially-swept artifact beats a killed
# process that never wrote one).
_T0 = time.perf_counter()
SWEEP_BUDGET_S = float(os.environ.get("PALLAS_CHECK_BUDGET_S", "330"))


def _budget_left():
    return SWEEP_BUDGET_S - (time.perf_counter() - _T0)


def fetch(x):
    """Host-sync: reduce to a scalar and pull it to the host."""
    leaves = jax.tree_util.tree_leaves(x)
    return float(sum(jnp.sum(jnp.abs(l).astype(jnp.float32)) for l in leaves))


def timeit(fn, *args, iters=20):
    fetch(fn(*args))                      # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    s = fetch(out)                        # host fetch closes the region
    dt = (time.perf_counter() - t0) / iters
    return dt, s


def maxdiff(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    return max(float(jnp.max(jnp.abs(x.astype(jnp.float32)
                                     - y.astype(jnp.float32))))
               for x, y in zip(fa, fb))


def check_flash_attention(results):
    from paddle_tpu.ops.pallas import flash_attn as fa
    B, N, H, D = 4, 1024, 8, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)

    for causal in (False, True):
        name = f"flash_attn_fwd{'_causal' if causal else ''}"
        pallas_fn = jax.jit(lambda q, k, v: fa._flash_attention_tpu(
            q, k, v, causal))
        ref_fn = jax.jit(lambda q, k, v: fa._ref_attention(q, k, v, causal))
        out_p = pallas_fn(q, k, v)
        out_r = ref_fn(q, k, v)
        md = maxdiff(out_p, out_r)
        tp, _ = timeit(pallas_fn, q, k, v)
        tr, _ = timeit(ref_fn, q, k, v)
        results[name] = {"ok": md < 3e-2, "maxdiff": md,
                         "pallas_ms": tp * 1e3, "xla_ms": tr * 1e3}

        # backward: full custom-vjp path vs XLA autodiff of the dense ref
        name = f"flash_attn_bwd{'_causal' if causal else ''}"
        loss_p = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(
                fa.flash_attention(q, k, v, causal).astype(jnp.float32)
                ** 2), argnums=(0, 1, 2)))
        loss_r = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(
                fa._ref_attention(q, k, v, causal).astype(jnp.float32)
                ** 2), argnums=(0, 1, 2)))
        gp = loss_p(q, k, v)
        gr = loss_r(q, k, v)
        md = maxdiff(gp, gr)
        tp, _ = timeit(loss_p, q, k, v)
        tr, _ = timeit(loss_r, q, k, v)
        results[name] = {"ok": md < 0.25, "maxdiff": md,
                         "pallas_ms": tp * 1e3, "xla_ms": tr * 1e3}


def check_flash_bench_shape(results):
    """Flash attention at the FLAGSHIP bench shape (bench.py: 1.3B config,
    [4, 2048, 16, 128] bf16 causal) with a block-size sweep — decides
    whether bench.py should flip use_flash on (r3 sweep: XLA fused
    attention won at this shape; re-measure after kernel changes)."""
    from paddle_tpu.ops.pallas import flash_attn as fa
    if jax.devices()[0].platform == "cpu":
        return
    B, N, H, D = 4, 2048, 16, 128
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, N, H, D) * 0.1, jnp.bfloat16)

    # forward sweep
    ref_fn = jax.jit(lambda q: fa._ref_attention(q, q, q, True))
    tr, _ = timeit(ref_fn, q, iters=10)
    entry = {"xla_fwd_ms": tr * 1e3, "fwd_blocks": {}}
    best = best_cfg = None
    # ordered by prior: the likely winners first, extras last so a
    # budget-starved (driver-default) run still measures the core set
    for bq, bk in ((256, 512), (512, 512), (512, 1024), (1024, 1024),
                   (2048, 512), (1024, 2048), (256, 1024), (2048, 1024),
                   (128, 512), (512, 2048)):
        if _budget_left() < 30:
            entry["fwd_blocks"][f"{bq}x{bk}"] = "skipped: budget"
            continue
        try:
            p_fn = jax.jit(lambda q, bq=bq, bk=bk: fa._flash_attention_tpu(
                q, q, q, True, block_q=bq, block_k=bk))
            tp, _ = timeit(p_fn, q, iters=10)
            entry["fwd_blocks"][f"{bq}x{bk}"] = tp * 1e3
            if best is None or tp * 1e3 < best:
                best, best_cfg = tp * 1e3, (bq, bk)
        except Exception as e:                      # noqa: BLE001
            entry["fwd_blocks"][f"{bq}x{bk}"] = f"{type(e).__name__}: {e}"
    entry["best_fwd_ms"] = best
    entry["best_fwd_blocks"] = best_cfg

    # Install the winning forward tiling BEFORE sweeping the backward:
    # bench.py installs best_fwd_blocks AND best_bwd_blocks together, so
    # the pair the gate approves must be the pair that was measured
    # (the probe's forward runs on the module defaults).
    if best_cfg is not None:
        fa.set_default_blocks(fwd=best_cfg)

    # backward sweep (full custom-vjp path vs XLA autodiff of the dense ref)
    def make_grad(f):
        return jax.jit(jax.grad(lambda q: jnp.sum(
            f(q).astype(jnp.float32) ** 2)))
    tr_b, _ = timeit(make_grad(lambda q: fa._ref_attention(q, q, q, True)),
                     q, iters=10)
    entry["xla_bwd_ms"] = tr_b * 1e3
    entry["bwd_blocks"] = {}
    best_b = best_b_cfg = None
    # sweep both backward strategies: split (dq + dkv kernels, each
    # recomputing the probability block) and fused (one kernel, p/ds
    # computed once, per-K-block dq partials reduced by XLA)
    for fused in (False, True):
        tag = "fused" if fused else "split"
        for bq, bk in ((256, 256), (512, 512), (512, 1024), (1024, 512),
                       (256, 512), (1024, 1024)):
            if _budget_left() < 30:
                entry["bwd_blocks"][f"{tag}:{bq}x{bk}"] = "skipped: budget"
                continue
            try:
                g_fn = make_grad(
                    lambda q, bq=bq, bk=bk, fused=fused:
                    fa._flash_fwd_bwd_probe(q, bq, bk, fused=fused))
                tb, _ = timeit(g_fn, q, iters=10)
                entry["bwd_blocks"][f"{tag}:{bq}x{bk}"] = tb * 1e3
                if best_b is None or tb * 1e3 < best_b:
                    best_b, best_b_cfg = tb * 1e3, (bq, bk, fused)
            except Exception as e:                  # noqa: BLE001
                entry["bwd_blocks"][f"{tag}:{bq}x{bk}"] = (
                    f"{type(e).__name__}: {e}")
    entry["best_bwd_ms"] = best_b
    entry["best_bwd_blocks"] = best_b_cfg[:2] if best_b_cfg else None
    entry["best_bwd_fused"] = bool(best_b_cfg[2]) if best_b_cfg else False
    starved = any(str(v).startswith("skipped: budget")
                  for blocks in (entry["fwd_blocks"], entry["bwd_blocks"])
                  for v in blocks.values())
    entry["budget_starved"] = starved
    if starved and (best is None or best_b is None):
        # zero measured configs is NOT an "XLA wins" verdict — record
        # null so a starved run is distinguishable from a measured loss
        # (the bench gate treats anything non-True as flash-off anyway)
        entry["pallas_beats_xla"] = None
    else:
        entry["pallas_beats_xla"] = bool(
            best is not None and best < entry["xla_fwd_ms"]
            and best_b is not None and best_b < entry["xla_bwd_ms"])
    results["flash_attn_bench_shape"] = entry


def check_fused_ffn(results):
    from paddle_tpu.ops.pallas import fused_ffn as ff
    M, Hd, F = 2048, 1024, 4096
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(M, Hd) * 0.1, jnp.bfloat16)
    w1 = jnp.asarray(rng.randn(Hd, F) * 0.02, jnp.bfloat16)
    b1 = jnp.asarray(rng.randn(F) * 0.01, jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(F, Hd) * 0.02, jnp.bfloat16)
    b2 = jnp.asarray(rng.randn(Hd) * 0.01, jnp.bfloat16)

    blocks = ff._pick_blocks(M, Hd, F, 2)
    assert blocks is not None, "fused_ffn: shape not tileable"
    pallas_fn = jax.jit(lambda *a: ff._fused_ffn_tpu(*a, *blocks,
                                                     interpret=False))
    ref_fn = jax.jit(ff._ref_ffn)
    out_p = pallas_fn(x, w1, b1, w2, b2)
    out_r = ref_fn(x, w1, b1, w2, b2)
    md = maxdiff(out_p, out_r)
    tp, _ = timeit(pallas_fn, x, w1, b1, w2, b2)
    tr, _ = timeit(ref_fn, x, w1, b1, w2, b2)
    results["fused_ffn_fwd"] = {"ok": md < 3e-2, "maxdiff": md,
                                "pallas_ms": tp * 1e3, "xla_ms": tr * 1e3}


def check_fused_ffn_bench_shape(results):
    """Fused FFN at the FLAGSHIP shape (1.3B config: tokens 6*2048 rows,
    hidden 2048, ffn 8192, bf16) with a tiling sweep — decides whether
    bench.py flips use_fused_ffn on.

    Times the full VALUE+GRAD step, not the forward alone: fused_ffn's
    custom vjp recomputes the forward inside the backward, so a forward
    win can still lose end-to-end (the flash gate learned this in r3).
    The winning config's FORWARD output is also parity-checked — the
    installed tiling must be the validated tiling."""
    from paddle_tpu.ops.pallas import fused_ffn as ff
    if jax.devices()[0].platform == "cpu":
        return
    if _budget_left() < 60:
        # no sweep budget: don't burn SIGKILL-bounded time compiling the
        # XLA baseline for a verdict that would be null anyway
        results["fused_ffn_bench_shape"] = {
            "budget_starved": True, "pallas_beats_xla": None}
        return
    M, Hd, F = 6 * 2048, 2048, 8192
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(M, Hd) * 0.1, jnp.bfloat16)
    w1 = jnp.asarray(rng.randn(Hd, F) * 0.02, jnp.bfloat16)
    b1 = jnp.asarray(rng.randn(F) * 0.01, jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(F, Hd) * 0.02, jnp.bfloat16)
    b2 = jnp.asarray(rng.randn(Hd) * 0.01, jnp.bfloat16)

    def make_step(fn):
        return jax.jit(jax.grad(
            lambda x, w1, b1, w2, b2: jnp.sum(
                fn(x, w1, b1, w2, b2).astype(jnp.float32) ** 2),
            argnums=(0, 1, 3)))

    tr, _ = timeit(make_step(ff._ref_ffn), x, w1, b1, w2, b2, iters=10)
    entry = {"xla_ms": tr * 1e3, "blocks": {}}
    best = best_cfg = None
    try:
        for bm in (128, 256, 512):
            for bf in (512, 256, 1024):
                if M % bm or F % bf:
                    continue
                if _budget_left() < 30:
                    entry["blocks"][f"{bm}x{bf}"] = "skipped: budget"
                    continue
                try:
                    ff.set_default_blocks((bm, bf))
                    step = make_step(
                        lambda *a: ff.fused_ffn(*a, interpret=False))
                    tp, _ = timeit(step, x, w1, b1, w2, b2, iters=10)
                    entry["blocks"][f"{bm}x{bf}"] = tp * 1e3
                    if best is None or tp * 1e3 < best:
                        best, best_cfg = tp * 1e3, (bm, bf)
                except Exception as e:              # noqa: BLE001
                    entry["blocks"][f"{bm}x{bf}"] = (
                        f"{type(e).__name__}: {e}")
        parity_ok = False
        if best_cfg is not None:
            # parity of the EXACT config the gate would install
            ff.set_default_blocks(best_cfg)
            md = maxdiff(ff.fused_ffn(x, w1, b1, w2, b2),
                         ff._ref_ffn(x, w1, b1, w2, b2))
            entry["best_maxdiff"] = md
            parity_ok = md < 3e-2
    finally:
        ff.set_default_blocks(None)
    entry["best_ms"] = best
    entry["best_blocks"] = best_cfg
    starved = any(str(v).startswith("skipped: budget")
                  for v in entry["blocks"].values())
    entry["budget_starved"] = starved
    if starved and best is None:
        entry["pallas_beats_xla"] = None
    else:
        entry["pallas_beats_xla"] = bool(
            best is not None and best < entry["xla_ms"] and parity_ok)
    results["fused_ffn_bench_shape"] = entry


def check_norms(results):
    from paddle_tpu.ops.pallas import norms
    M, Hd = 4096, 1024
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(M, Hd), jnp.float32)
    g = jnp.asarray(rng.randn(Hd) * 0.1 + 1.0, jnp.float32)
    b = jnp.asarray(rng.randn(Hd) * 0.1, jnp.float32)

    for name, p_fn, r_fn in [
        ("layer_norm",
         jax.jit(lambda x, g, b: norms.layer_norm(x, g, b)),
         jax.jit(lambda x, g, b: norms._ref_layer_norm(x, g, b, 1e-5))),
    ]:
        out_p = p_fn(x, g, b)
        out_r = r_fn(x, g, b)
        md = maxdiff(out_p, out_r)
        tp, _ = timeit(p_fn, x, g, b)
        tr, _ = timeit(r_fn, x, g, b)
        results[name] = {"ok": md < 1e-4, "maxdiff": md,
                         "pallas_ms": tp * 1e3, "xla_ms": tr * 1e3}

    p_fn = jax.jit(lambda x, g: norms.rms_norm(x, g))
    r_fn = jax.jit(lambda x, g: norms._ref_rms_norm(x, g, 1e-6))
    md = maxdiff(p_fn(x, g), r_fn(x, g))
    tp, _ = timeit(p_fn, x, g)
    tr, _ = timeit(r_fn, x, g)
    results["rms_norm"] = {"ok": md < 1e-4, "maxdiff": md,
                           "pallas_ms": tp * 1e3, "xla_ms": tr * 1e3}


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", file=sys.stderr)
    if dev.platform == "cpu":
        print("WARNING: no TPU — kernels will run their XLA fallbacks only",
              file=sys.stderr)

    # CPU runs only exercise fallbacks — never clobber the committed
    # on-chip results
    suffix = ".json" if dev.platform != "cpu" else "_cpu.json"
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tpu_kernel_check" + suffix)

    results = {"device": str(dev.device_kind)}
    # Most-important check first (the bench-shape sweep drives the
    # use_flash gate) and the artifact is rewritten after EVERY check —
    # if the orchestrator SIGKILLs us mid-run, the completed checks
    # survive on disk instead of vanishing with the process.
    for check in (check_flash_bench_shape, check_fused_ffn_bench_shape,
                  check_flash_attention, check_fused_ffn, check_norms):
        try:
            check(results)
        except Exception as e:                      # noqa: BLE001
            results[check.__name__] = {"ok": False,
                                       "error": f"{type(e).__name__}: {e}"}
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:       # atomic replace: a SIGKILL mid-
            json.dump(results, f, indent=2, default=str)
        os.replace(tmp, out_path)       # write can't corrupt the artifact
    ok = all(v.get("ok", True) for v in results.values()
             if isinstance(v, dict))
    for k, v in results.items():
        if isinstance(v, dict) and "ok" in v:
            status = "PASS" if v["ok"] else "FAIL"
            extra = (f" pallas={v.get('pallas_ms', 0):.3f}ms"
                     f" xla={v.get('xla_ms', 0):.3f}ms"
                     f" maxdiff={v.get('maxdiff', 0):.2e}"
                     if "pallas_ms" in v else f" {v.get('error', '')}")
            print(f"{status} {k}{extra}")
    print("ALL OK" if ok else "FAILURES PRESENT")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
