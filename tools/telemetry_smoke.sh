#!/usr/bin/env bash
# Telemetry smoke: a 5-step CPU-mesh training run with the unified
# telemetry layer on, inside a hard 60s budget — CI's proof that the
# metrics registry, the StepTimer JSONL event log and the report tool
# still work end to end.
#
# Asserts: (1) the run's JSONL event log parses line by line and holds
# one record per step; (2) fast_path_summary() equals the registry
# snapshot (the legacy views are served from the registry, no dual
# bookkeeping); (3) tools/telemetry_report.py renders the dir and exits
# 0, naming this rank's step times.
#
# Usage: tools/telemetry_smoke.sh
set -o pipefail
cd "$(dirname "$0")/.." || exit 2
REPO=$(pwd)

TDIR=$(mktemp -d /tmp/telemetry_smoke.XXXXXX)
trap 'rm -rf "$TDIR"' EXIT

# same env scrub as testing/env.clean_cpu_env: forced CPU backend, the
# container's sitecustomize dropped from PYTHONPATH
run_py() {
    timeout -k 5 50 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
        XLA_FLAGS="--xla_force_host_platform_device_count=4" \
        PADDLE_TELEMETRY_DIR="$TDIR" python "$@"
}

run_py - <<'PY' || { echo "telemetry_smoke: FAIL (training)" >&2; exit 1; }
import json, os
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.observability import StepTimer, metrics, aggregate

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(16, 16), paddle.nn.Tanh(),
                           paddle.nn.Linear(16, 4))
opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
rng = np.random.RandomState(0)
with StepTimer(name="smoke", tokens_per_step=8 * 16) as timer:
    for step in range(5):
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        with timer.step():
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
assert timer.steps == 5, timer.steps

# the legacy views ARE the registry: every raw counter the registry
# holds for a family must equal what fast_path_summary() serves
summary = profiler.fast_path_summary()
fams = metrics.families()
flat_summary = dict(summary)
flat_summary.update({"watchdog": summary["faults"],
                     "launch": summary["faults"],
                     "checkpoint": summary["faults"],
                     "bootstrap": summary["faults"],
                     "faults": summary["faults"]})
for fam, keys in fams.items():
    view = flat_summary.get(fam)
    if view is None:
        continue
    for k, v in keys.items():
        assert view.get(k) == v, (fam, k, v, view.get(k))
print("# registry == fast_path_summary views: OK")

aggregate.publish(step=5)        # snapshot file for the report tool
print("# prometheus export bytes:", len(metrics.to_prometheus()))
PY

# every JSONL line must parse; the log must hold 5 step records
run_py - <<PY || { echo "telemetry_smoke: FAIL (jsonl)" >&2; exit 1; }
import glob, json
steps = 0
files = glob.glob("$TDIR/events_rank*.jsonl")
assert files, "no event log written"
for path in files:
    for line in open(path):
        rec = json.loads(line)
        steps += rec.get("event") == "step"
assert steps == 5, f"expected 5 step records, found {steps}"
print("# jsonl parses:", steps, "steps")
PY

run_py tools/telemetry_report.py "$TDIR" \
    || { echo "telemetry_smoke: FAIL (report tool)" >&2; exit 1; }
run_py tools/telemetry_report.py "$TDIR" --json >/dev/null \
    || { echo "telemetry_smoke: FAIL (report --json)" >&2; exit 1; }

echo "telemetry_smoke: OK"
