#!/bin/bash
# Watch for the axon relay coming alive: poll listening TCP ports every 30s,
# log any change to tools/relay_watch.log. The relay (outer-driver-spawned
# stdio bridge) listens on localhost high ports (8082-range historically);
# when a new port appears, it's the signal to run bench.py immediately.
LOG=/root/repo/tools/relay_watch.log
prev=""
while true; do
  cur=$(python3 - <<'EOF'
ports = set()
for f in ("/proc/net/tcp", "/proc/net/tcp6"):
    try:
        with open(f) as fh:
            for line in fh.readlines()[1:]:
                parts = line.split()
                if parts[3] == "0A":
                    ports.add(int(parts[1].rsplit(":", 1)[1], 16))
    except OSError:
        pass
print(" ".join(str(p) for p in sorted(ports)))
EOF
)
  if [ "$cur" != "$prev" ]; then
    echo "$(date -u +%FT%TZ) listening: $cur" >> "$LOG"
    prev="$cur"
  fi
  sleep 30
done
