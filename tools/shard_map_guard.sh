#!/usr/bin/env bash
# Standing-constraint guard (ROADMAP): version-moving jax APIs must route
# through paddle_tpu/framework/jax_compat.py.  This greps the package for
# direct imports/uses of the moving names — jax.experimental.shard_map
# (renamed to jax.shard_map upstream), bare "from jax import shard_map",
# and direct jax.lax.psum_scatter outside the compat shim — and fails CI
# on any hit outside framework/jax_compat.py.
#
# Usage: tools/shard_map_guard.sh   (run from anywhere; cd's to the repo)
# Exit:  0 clean, 1 on violations (each printed with file:line).
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

fail=0

check() {
    local pattern="$1" why="$2"
    # grep the python package, excluding the one module allowed to pin
    # the moving spelling (and caches/this guard's own docs)
    hits=$(grep -rnE "$pattern" paddle_tpu \
        --include='*.py' \
        | grep -v 'framework/jax_compat.py' \
        | grep -v '__pycache__' || true)
    if [ -n "$hits" ]; then
        echo "shard_map_guard: $why" >&2
        echo "$hits" >&2
        fail=1
    fi
}

check 'jax\.experimental\.shard_map' \
    "direct jax.experimental.shard_map import (use framework.jax_compat.shard_map)"
check 'from jax import shard_map|jax\.shard_map\(' \
    "direct jax.shard_map usage (use framework.jax_compat.shard_map)"
check 'jax\.lax\.psum_scatter' \
    "direct jax.lax.psum_scatter (use framework.jax_compat.psum_scatter)"

if [ "$fail" -ne 0 ]; then
    echo "shard_map_guard: FAIL" >&2
    exit 1
fi
echo "shard_map_guard: OK"
