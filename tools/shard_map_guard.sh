#!/usr/bin/env bash
# Standing-constraint guard (ROADMAP): version-moving jax APIs must route
# through paddle_tpu/framework/jax_compat.py.
#
# Now a thin wrapper over the PTL001 moving-api rule of the AST static
# analyzer (python -m paddle_tpu.analysis --rules=moving-api), which
# resolves imports, aliases and attribute chains — so the aliased
# spellings the old grep provably missed (`from jax.experimental import
# shard_map as sm`, `from jax.sharding import NamedSharding`,
# `import jax; jax.sharding.Mesh(...)`) all fail too.  Same contract as
# the grep version: hits on stderr, "shard_map_guard: OK"/": FAIL",
# exit 0 clean / 1 violations / 2 environment error.
#
# Usage: tools/shard_map_guard.sh [paths...]   (default: paddle_tpu)
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

targets=("$@")
[ ${#targets[@]} -eq 0 ] && targets=(paddle_tpu)

# tools/ptl_lint.py standalone-loads the same `python -m
# paddle_tpu.analysis` CLI WITHOUT importing the paddle_tpu package —
# so the guard needs no jax (like the grep it replaced) and a missing
# interpreter dep surfaces as exit 2, never as phantom violations
out=$(python tools/ptl_lint.py "${targets[@]}" --rules=moving-api 2>&1)
rc=$?
if [ "$rc" -eq 1 ]; then
    # the analyzer's documented "findings" exit — everything else
    # (argparse usage=2, crash traceback, missing interpreter=127)
    # is an environment problem, not a violation
    echo "shard_map_guard: direct version-moving jax API outside" \
         "framework/jax_compat.py (route through the compat shim):" >&2
    echo "$out" >&2
    echo "shard_map_guard: FAIL" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "shard_map_guard: analyzer failed to run (exit $rc):" >&2
    echo "$out" >&2
    exit 2
fi
echo "shard_map_guard: OK"
