#!/usr/bin/env bash
# Serving smoke: 30 mixed-length requests through the continuous-batching
# engine on CPU, inside a hard 100s budget — CI's proof that the slot
# scheduler, the bucketed prefill ladder, the serving.* telemetry family
# and the persistent compilation cache still work end to end.
#
# Asserts: (1) all 30 requests complete with the requested token counts;
# (2) slot occupancy really exceeded 1 (continuous batching happened, not
# serial decode); (3) prefill compiles stay bounded by the bucket-ladder
# size and the decode step compiled exactly once; (4) the JSONL telemetry
# the run wrote parses line by line and holds serving_step records;
# (5) a SECOND engine in the same PADDLE_JIT_CACHE_DIR warm-starts with
# zero persistent-cache misses.
#
# Usage: tools/serving_smoke.sh
set -o pipefail
cd "$(dirname "$0")/.." || exit 2
REPO=$(pwd)

TDIR=$(mktemp -d /tmp/serving_smoke.XXXXXX)
trap 'rm -rf "$TDIR"' EXIT
mkdir -p "$TDIR/telemetry" "$TDIR/jit_cache"

# same env scrub as testing/env.clean_cpu_env: forced CPU backend, the
# container's sitecustomize dropped from PYTHONPATH
run_py() {
    timeout -k 5 90 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
        PADDLE_TELEMETRY_DIR="$TDIR/telemetry" \
        PADDLE_JIT_CACHE_DIR="$TDIR/jit_cache" python "$@"
}

run_py - <<'PY' || { echo "serving_smoke: FAIL (engine)" >&2; exit 1; }
import numpy as np
import jax
from paddle_tpu.models import gpt as G
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.observability import metrics

SEQ, BATCH = (8, 16), (1, 2)
cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                  num_heads=2, max_seq_len=64, dtype="float32",
                  use_flash=False, remat=False)
params = G.init_params(cfg, jax.random.PRNGKey(0))
eng = ServingEngine((params, cfg), slots=4, max_len=32, seq_buckets=SEQ,
                    batch_buckets=BATCH)
rng = np.random.RandomState(0)
reqs = [eng.submit(rng.randint(1, 256, rng.randint(3, 15)).astype(np.int32),
                   int(rng.randint(3, 9))) for _ in range(30)]
done = eng.run()
st = eng.stats()
assert len(done) == 30, len(done)
for r in reqs:
    assert r.done and len(r.tokens) == r.max_new_tokens, (r.id, r.tokens)
assert st["slot_occupancy_peak"] > 1, st       # continuous batching happened
assert st["decode_compiles"] == 1, st
assert st["prefill_compiles"] <= len(SEQ) * len(BATCH), st
hits = metrics.counter("compile.persistent_cache_hits").value
miss = metrics.counter("compile.persistent_cache_misses").value
print(f"# serving_smoke: 30 requests ok, occupancy_peak="
      f"{st['slot_occupancy_peak']}, prefill_compiles="
      f"{st['prefill_compiles']}, cache hits={hits} misses={miss}")
PY

# warm restart: a fresh process over the same PADDLE_JIT_CACHE_DIR must
# reload every executable (zero persistent-cache misses)
run_py - <<'PY' || { echo "serving_smoke: FAIL (warm restart)" >&2; exit 1; }
import numpy as np
import jax
from paddle_tpu.models import gpt as G
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.observability import metrics

cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                  num_heads=2, max_seq_len=64, dtype="float32",
                  use_flash=False, remat=False)
params = G.init_params(cfg, jax.random.PRNGKey(0))
eng = ServingEngine((params, cfg), slots=4, max_len=32, seq_buckets=(8, 16),
                    batch_buckets=(1, 2))
rng = np.random.RandomState(1)
for _ in range(6):
    eng.submit(rng.randint(1, 256, rng.randint(3, 15)).astype(np.int32), 4)
eng.run()
hits = metrics.counter("compile.persistent_cache_hits").value
miss = metrics.counter("compile.persistent_cache_misses").value
assert miss == 0, f"warm restart recompiled: {miss} cache misses"
assert hits > 0, "persistent cache never consulted"
print(f"# serving_smoke: warm restart ok ({hits} cache hits, 0 misses)")
PY

# every JSONL line must parse; the log must hold serving_step records
run_py - <<PY || { echo "serving_smoke: FAIL (jsonl)" >&2; exit 1; }
import glob, json
steps = 0
files = glob.glob("$TDIR/telemetry/events_rank*.jsonl")
assert files, "no event log written"
for path in files:
    for line in open(path):
        rec = json.loads(line)
        steps += rec.get("event") == "serving_step"
assert steps > 5, f"expected serving_step records, found {steps}"
print("# jsonl parses:", steps, "serving steps")
PY

echo "serving_smoke: OK"
