"""Docstring-example conformance: run the REFERENCE'S own code examples
verbatim against this framework (through the `paddle` alias).

Extracts every ``.. code-block:: python`` example from the reference
tree's docstrings (skipping obviously-unrunnable ones: downloads, GPU
pinning, interactive loops), executes each in a fresh namespace inside
one interpreter, and prints a pass/fail tally plus the failure
clusters.  This is the broadest black-box parity check available: the
examples were written by the reference's authors to demonstrate exact
API contracts.

Run:  PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
          python tools/docstring_conformance.py [N] [START]
"""
import ast
import contextlib
import io
import json
import os
import re
import signal
import sys
import traceback
from collections import Counter

REF = "/root/reference/python/paddle"
SKIP_PAT = re.compile(
    r"cuda|gpu|\.download|urllib|requests|DataLoader\(.*num_workers=[1-9]|"
    r"dataset\.(flowers|imdb|wmt|movielens|conll05|sentiment)|"
    r"import paddlehub|paddle\.utils\.download|plt\.|matplotlib|"
    r"fluid\.io\.load|load_inference_model|save_inference_model|"
    r"\.\.\.|print\(paddle\.__version__|distributed\.launch|"
    r"init_parallel_env|spawn|ParallelEnv|nccl|data_layer|while True",
    re.I)


def extract_examples():
    out = []
    for root, _, files in os.walk(REF):
        if "tests" in root or "incubate" in root:
            continue
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            try:
                tree = ast.parse(open(path, encoding="utf-8").read())
            except Exception:                              # noqa: BLE001
                continue
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.ClassDef,
                                         ast.Module)):
                    continue
                doc = ast.get_docstring(node)
                if not doc or ".. code-block:: python" not in doc:
                    continue
                for block in doc.split(".. code-block:: python")[1:]:
                    lines = block.splitlines()[1:]
                    code = []
                    for ln in lines:
                        if ln.strip() == "":
                            code.append("")
                            continue
                        if not ln.startswith((" ", "\t")):
                            break
                        code.append(ln)
                    body = [l for l in code if l.strip()]
                    if not body:
                        continue
                    indent = min(len(l) - len(l.lstrip()) for l in body)
                    snippet = "\n".join(l[indent:] if len(l) > indent else l
                                        for l in code)
                    if SKIP_PAT.search(snippet) or "import" not in snippet:
                        continue
                    out.append({"file": os.path.relpath(path, REF),
                                "name": getattr(node, "name", "module"),
                                "code": snippet})
    return out


class _Timeout(Exception):
    pass


def main():
    examples = extract_examples()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else len(examples)
    start = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    signal.signal(signal.SIGALRM,
                  lambda *a: (_ for _ in ()).throw(_Timeout()))

    import paddle  # the alias package

    ok, fails = 0, []
    for ex in examples[start:start + n]:
        ns = {"__name__": "__main__"}
        buf = io.StringIO()
        signal.alarm(25)
        try:
            with contextlib.redirect_stdout(buf), \
                    contextlib.redirect_stderr(buf):
                exec(compile(ex["code"],
                             f"<{ex['file']}:{ex['name']}>", "exec"), ns)
            ok += 1
        except _Timeout:
            fails.append({**ex, "err": "TIMEOUT"})
        except Exception as e:                             # noqa: BLE001
            fails.append({**ex,
                          "err": f"{type(e).__name__}: {e}"[:240],
                          "tb": traceback.format_exc(limit=3)[-600:]})
        finally:
            signal.alarm(0)
            try:
                paddle.disable_static()
            except Exception:                              # noqa: BLE001
                pass

    total = min(n, len(examples) - start)
    print(f"doc-example conformance: {ok}/{total} pass "
          f"({100.0 * ok / max(total, 1):.1f}%)")
    for msg, cnt in Counter(f["err"][:72] for f in fails).most_common(20):
        print(f"  {cnt:4d}  {msg}")
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "docstring_conformance_results.json"),
              "w") as f:
        json.dump({"ok": ok, "total": total, "fails": fails}, f, indent=1)


if __name__ == "__main__":
    main()
