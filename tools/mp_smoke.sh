#!/usr/bin/env bash
# Model-parallel smoke: the composed TP+PP+ZeRO train step on the
# 2x2x2 CPU mesh, inside a hard 120s budget — CI's proof that the
# distributed/auto subsystem still trains, matches single-device
# numerics, shards optimizer state, and publishes its collective plan.
#
# Runs bench.py --model-parallel (--cpu-mesh 8 re-execs with a clean
# forced-CPU env, same dance as tests/conftest.py): 5 training steps
# with tensor parallelism (tp=2 Megatron splits), a 2-stage 1F1B
# pipeline (pp=2) and ZeRO-2 dp-sharded Adam moments (dp=2) on a gpt
# config whose replicated params+moments exceed the simulated per-device
# budget.  The bench itself asserts loss parity vs a single-device
# reference (1e-5), the >=1.9x optimizer-state bytes/device shrink, and
# plan-exact sharding.* counters; this smoke additionally greps the
# parsed JSON metric line and the parity/counters attestation.
#
# Usage: tools/mp_smoke.sh
# Exit:  bench exit status, or 1 if the metric line / attestation is
#        missing.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

LOG=$(mktemp /tmp/mp_smoke.XXXXXX.log)
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python bench.py --model-parallel --cpu-mesh 8 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

if [ "$rc" -ne 0 ]; then
    echo "mp_smoke: FAIL (rc=$rc)" >&2
    exit "$rc"
fi
if ! grep -q '"metric": "model_parallel_step_time_ms"' "$LOG"; then
    echo "mp_smoke: FAIL — run finished but emitted no parsed" \
         "model_parallel_step_time_ms metric line" >&2
    exit 1
fi
if ! grep -q 'sharding counters nonzero and plan-exact' "$LOG"; then
    echo "mp_smoke: FAIL — no parity/counters attestation" >&2
    exit 1
fi
echo "mp_smoke: OK"
