#!/usr/bin/env bash
# Speculative-decoding smoke: both drafting modes on the CPU backend,
# inside a hard 55s budget — CI's proof that speculation (ISSUE 13)
# still commits >1.5 accepted tokens per verify step on repetitive
# traffic while staying token-exact with the non-speculative paged
# engine, inside the fixed executable set (ONE donated verify step,
# never a compile per accept length).
#
# Runs bench.py --serving's speculation phase only
# (BENCH_SERVING_PHASES=spec; the base/paged/quant trio is the nightly
# bench's job), with the int8 leg ON (it is the page-byte/prefix-hash
# attestation's live half — the byte-exact half lives in
# tests/test_speculative.py) and a telemetry dir so the serving_step
# JSONL events can be grepped for the new drafted/accepted fields.
#
# Usage: tools/spec_smoke.sh
# Exit:  bench exit status, or 1 if the metric line / attestations /
#        JSONL fields are missing.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

LOG=$(mktemp /tmp/spec_smoke.XXXXXX.log)
TEL=$(mktemp -d /tmp/spec_smoke_tel.XXXXXX)
timeout -k 10 55 env JAX_PLATFORMS=cpu \
    BENCH_SERVING_PHASES=spec BENCH_SPEC_REQUESTS=8 \
    PADDLE_TELEMETRY_DIR="$TEL" \
    python bench.py --serving 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

if [ "$rc" -ne 0 ]; then
    echo "spec_smoke: FAIL (rc=$rc)" >&2
    exit "$rc"
fi
if ! grep -q '"metric": "serving_spec_accepted_tokens_per_step"' "$LOG"; then
    echo "spec_smoke: FAIL — no parsed" \
         "serving_spec_accepted_tokens_per_step metric line" >&2
    exit 1
fi
if ! grep -q '"parity": "token-exact"' "$LOG"; then
    echo "spec_smoke: FAIL — metric line does not attest token-exact" \
         "parity vs the non-speculative paged engine" >&2
    exit 1
fi
for mode in ngram draft; do
    if ! grep -q "# serving/spec $mode: .*(>1.5)" "$LOG"; then
        echo "spec_smoke: FAIL — no accepted-rate attestation for the" \
             "$mode drafting mode" >&2
        exit 1
    fi
done
if ! grep -q '"greedy_match_vs_nonspec_int8": true' "$LOG"; then
    echo "spec_smoke: FAIL — metric line does not attest int8 spec" \
         "parity vs the non-speculative int8 engine" >&2
    exit 1
fi
for field in drafted accepted committed; do
    if ! grep -h '"event": "serving_step"' "$TEL"/*.jsonl \
            | grep -q "\"$field\""; then
        echo "spec_smoke: FAIL — serving_step JSONL events do not" \
             "carry the $field speculation field" >&2
        exit 1
    fi
done
rm -rf "$TEL"
echo "spec_smoke: OK"
