#!/usr/bin/env bash
# Pipeline-stage serving smoke: the ISSUE-20 tentpole on a 2x2 pp x tp
# CPU mesh, inside a hard 60s budget — CI's proof that a gpt config too
# big for an entire tp=2 tier still serves token-exact when depth
# splits into 1F1B stage rows INSIDE the one donated decode executable.
#
# Runs bench.py --serving with only the pp phase (--cpu-mesh 4 re-execs
# with a clean forced-CPU env, same dance as tests/conftest.py).  The
# phase itself asserts full fp32 bytes > the 2-device tier budget,
# every stage row under the per-device budget, decode_compiles == 1
# across all stages, zero steady-state compiles, and greedy parity vs
# models.gpt.generate; this smoke additionally greps the parsed
# serving_pp_tokens_per_sec metric line and the per-stage attestation.
#
# Usage: tools/ppserve_smoke.sh
# Exit:  bench exit status, or 1 if the metric line / attestation is
#        missing.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

LOG=$(mktemp /tmp/ppserve_smoke.XXXXXX.log)
timeout -k 10 60 env JAX_PLATFORMS=cpu BENCH_SERVING_PHASES=pp \
    python bench.py --serving --cpu-mesh 4 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

if [ "$rc" -ne 0 ]; then
    echo "ppserve_smoke: FAIL (rc=$rc)" >&2
    exit "$rc"
fi
if ! grep -q '"metric": "serving_pp_tokens_per_sec"' "$LOG"; then
    echo "ppserve_smoke: FAIL — run finished but emitted no parsed" \
         "serving_pp_tokens_per_sec metric line" >&2
    exit 1
fi
if ! grep -q 'decode_compiles=1 across all 2 stages' "$LOG"; then
    echo "ppserve_smoke: FAIL — no per-stage compile attestation" >&2
    exit 1
fi
if ! grep -q 'token-exact vs single-device' "$LOG"; then
    echo "ppserve_smoke: FAIL — no token-parity attestation" >&2
    exit 1
fi
echo "ppserve_smoke: OK"
