#!/usr/bin/env python
"""Jax-less entry point for the compile-hygiene analyzer.

``python -m paddle_tpu.analysis`` imports the paddle_tpu package first,
and the package __init__ imports jax — fine in the CI container, fatal
on a bare-python box.  This bootstrap loads the analysis module tree
STANDALONE (the analysis package is stdlib-only by design; same
importlib trick as tools/telemetry_report.py uses for observability)
so lint runs anywhere:

    python tools/ptl_lint.py paddle_tpu tools bench.py

Identical flags and exit codes to the ``-m`` form (see cli.py); the
only behavior difference is that the ``analysis.*`` registry family is
not published (no package, no registry).
"""
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    pkg_dir = os.path.join(REPO, "paddle_tpu", "analysis")
    name = "_ptl_analysis"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    analysis = _load_analysis()
    from _ptl_analysis.cli import main
    sys.exit(main())
