"""Sweep bench variants on the live chip (run each in a fresh process).

Usage: python tools/bench_sweep.py '<variant-json>'
  variant keys: hidden, layers, heads, seq, batch, steps, remat (bool),
  remat_policy, param_dtype, moment_dtype, disable_pallas
Prints one JSON result line.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

v = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
if v.get("disable_pallas"):
    os.environ["PADDLE_TPU_DISABLE_PALLAS"] = "1"

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh import create_mesh
from paddle_tpu.models import gpt, gpt_hybrid

cfg = gpt.GPTConfig(
    vocab_size=50304,
    hidden_size=v.get("hidden", 2048),
    num_layers=v.get("layers", 24),
    num_heads=v.get("heads", 16),
    max_seq_len=v.get("seq", 2048),
    param_dtype=v.get("param_dtype", "bfloat16"),
    remat=v.get("remat", True),
    remat_policy=v.get("remat_policy", "full"),
)
batch = v.get("batch", 4)
steps = v.get("steps", 8)
moment_dtype = jnp.dtype(v.get("moment_dtype", "bfloat16"))

dev = jax.devices()[0]
mesh = create_mesh(dp=1, tp=1, pp=1, sp=1, devices=[dev])
params, m, mv = gpt_hybrid.init_sharded(cfg, mesh, jax.random.PRNGKey(0),
                                        moment_dtype=moment_dtype)
step = gpt_hybrid.make_train_step(cfg, mesh, n_microbatch=1,
                                  xent_chunks=v.get("xent_chunks", 1))
N = cfg.max_seq_len
toks = jnp.asarray(np.random.RandomState(0).randint(
    0, cfg.vocab_size, (batch, N)), jnp.int32)
lr = jnp.float32(1e-4)

params, m, mv, loss = step(params, m, mv, jnp.int32(1), toks, toks, lr)
float(loss)
t0 = time.perf_counter()
for i in range(steps):
    params, m, mv, loss = step(params, m, mv, jnp.int32(i + 2), toks, toks, lr)
fl = float(loss)
dt = time.perf_counter() - t0
tps = batch * N * steps / dt
from bench import _peak_flops
mfu = tps * cfg.flops_per_token() / _peak_flops(dev)
print(json.dumps({"variant": v, "tokens_per_sec": round(tps, 1),
                  "mfu": round(mfu, 4), "loss": round(fl, 4),
                  "step_ms": round(dt / steps * 1e3, 1)}))
