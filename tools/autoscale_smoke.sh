#!/usr/bin/env bash
# Autoscale smoke: SLO-driven fleet elasticity on the CPU backend,
# inside a hard 120s budget — CI's proof that the autoscaler (ISSUE 11)
# still scales a serving fleet 2 -> 4 -> 2 under a generated 3x Poisson
# burst while honoring the durability + priority contracts.
#
# Runs bench.py --fleet's autoscale phase only (BENCH_FLEET_PHASES=
# autoscale; the static-baseline goodput comparison is skipped via
# BENCH_AS_STATIC=0 to fit the budget — the nightly bench keeps it).
# The bench itself asserts: interactive p99 under the SLO target,
# replicas_up rises during the burst and falls back to the minimum
# after cooldown, every scale-up replica joins warm from the shared
# persistent compilation cache, and NO admitted request is lost.  This
# script additionally greps the parsed JSON metric line for the
# zero-lost and batch-only-shed attestations.
#
# Usage: tools/autoscale_smoke.sh
# Exit:  bench exit status, or 1 if the metric line / attestations are
#        missing.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

LOG=$(mktemp /tmp/autoscale_smoke.XXXXXX.log)
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    BENCH_FLEET_PHASES=autoscale BENCH_AS_STATIC=0 \
    BENCH_AS_MIN=2 BENCH_AS_MAX=4 BENCH_AS_DURATION_S=12 \
    python bench.py --fleet --cpu-mesh 2 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

if [ "$rc" -ne 0 ]; then
    echo "autoscale_smoke: FAIL (rc=$rc)" >&2
    exit "$rc"
fi
if ! grep -q '"metric": "fleet_autoscale_goodput_tps"' "$LOG"; then
    echo "autoscale_smoke: FAIL — no parsed fleet_autoscale_goodput_tps" \
         "metric line" >&2
    exit 1
fi
if ! grep -q '"lost_requests": 0' "$LOG"; then
    echo "autoscale_smoke: FAIL — metric line does not attest zero lost" \
         "requests through the scale up/down cycle" >&2
    exit 1
fi
if ! grep -q '"interactive": 0' "$LOG"; then
    echo "autoscale_smoke: FAIL — metric line does not attest that the" \
         "interactive class was never shed" >&2
    exit 1
fi
echo "autoscale_smoke: OK"
