#!/usr/bin/env bash
# Distributed-tracing smoke (ISSUE 19, CPU): boot a traced 2-replica
# prefill/decode fleet (PADDLE_TRACE=1), drain a few requests, and
# assert the tracing contract end to end:
#   - every lifecycle assembles causally ordered across the three
#     processes: admit -> dispatch -> prefill_done -> park -> ship ->
#     inject -> completion -> ack, zero negative spans after clock
#     correction, phases telescope exactly to the measured e2e
#   - tools/trace_report.py renders the attribution over the same dir
#   - an injected router kill with in-flight work (fleet._crash(), the
#     SIGKILL simulation; the real-signal path runs in
#     routerchaos_smoke.sh) leaves a flight_router_recovery_*.json
#     dump naming EVERY in-flight request id, and the gen-2 router
#     still serves them to completion
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

WORK=$(mktemp -d "${TMPDIR:-/tmp}/paddle_tpu_trace_smoke.XXXXXX")
trap 'rm -rf "$WORK"' EXIT
LOG="$WORK/smoke.log"

timeout -k 10 240 env JAX_PLATFORMS=cpu python - "$WORK" >"$LOG" 2>&1 <<'PY'
import glob
import json
import os
import sys

import numpy as np

REPO = os.getcwd()
sys.path.insert(0, REPO)

from paddle_tpu.inference.fleet import ServingFleet
from paddle_tpu.observability import aggregate, timeline, tracing
from paddle_tpu.testing.env import clean_cpu_env

work = sys.argv[1]
tdir = os.path.join(work, "telemetry")
jd = os.path.join(work, "wal")
os.environ["PADDLE_TELEMETRY_DIR"] = tdir
os.environ["PADDLE_TRACE"] = "1"
timeline.configure(tdir)

env = clean_cpu_env(REPO, device_count=1)
env.pop("PADDLE_FAULTS", None)
env["PADDLE_TELEMETRY_DIR"] = tdir
env["PADDLE_TRACE"] = "1"

SPEC = {"cfg": {"vocab_size": 256, "hidden_size": 32, "num_layers": 2,
                "num_heads": 2, "max_seq_len": 128, "dtype": "float32",
                "use_flash": False, "remat": False},
        "seed": 0, "paged": True, "slots": 3, "max_len": 64,
        "page_size": 8, "seq_buckets": [8, 16], "batch_buckets": [1, 2]}


def fleet(tag):
    return ServingFleet(SPEC, roles=["prefill", "decode"], env_base=env,
                        journal_dir=jd,
                        log_dir=os.path.join(work, tag, "logs"),
                        heartbeat_s=30, restart_backoff_s=0.2)


rng = np.random.RandomState(3)
f1 = fleet("gen1")
assert f1.await_healthy(timeout=180) == 2
for i in range(3):
    f1.submit(rng.randint(1, 256, 6), 8, request_id=f"traced-{i}")
done, failed = f1.drain(timeout=180)
assert not failed and len(done) == 3, (sorted(done), failed)

# --- lifecycle assembly: hop order, causality, telescoping sums ---
lcs = [lc for lc in aggregate.assemble_traces(tdir)
       if (lc["request_id"] or "").startswith("traced-")]
assert len(lcs) == 3, [lc["request_id"] for lc in lcs]
HOPS = ("admit", "dispatch", "prefill_done", "park", "ship", "inject",
        "completion", "ack")
for lc in lcs:
    hops = lc["hops"]
    idx = []
    for h in HOPS:
        assert h in hops, (lc["request_id"], h, hops)
        idx.append(hops.index(h))
    assert idx == sorted(idx), (lc["request_id"], hops)
    assert lc["negative_spans"] == 0, lc
    s = sum(lc["phases"].values())
    assert abs(s - lc["e2e_s"]) < 1e-4, (s, lc["e2e_s"], lc["phases"])
print(f"# trace_smoke: {len(lcs)} lifecycles causally ordered "
      f"(prefill_done -> park -> ship -> inject -> completion -> ack) "
      f"across 3 processes, 0 negative spans, phases telescope to e2e")

# --- injected router kill: flight dump names every in-flight id ---
inflight = ["inflight-0", "inflight-1"]
for rid in inflight:
    f1.submit(rng.randint(1, 256, 5), 6, request_id=rid)
f1._crash()

f2 = fleet("gen2")
try:
    done2, failed2 = f2.drain(timeout=180)
    assert not failed2, failed2
    assert all(r in done2 for r in inflight), (sorted(done2), inflight)
    assert f2.stats()["router_recoveries"] == 1, f2.stats()
finally:
    f2.close()
    f1.close()          # reaps the crashed gen-1's worker bookkeeping

dumps = sorted(glob.glob(
    os.path.join(tdir, "flight_router_recovery_*.json")))
assert dumps, sorted(os.listdir(tdir))
with open(dumps[-1], encoding="utf-8") as f:
    payload = json.load(f)
got = set(payload.get("inflight") or [])
assert got == set(inflight), (sorted(got), inflight)
assert payload.get("ring"), "flight dump carries no ring evidence"
print(f"# trace_smoke: router kill -> {os.path.basename(dumps[-1])} "
      f"names every in-flight id {sorted(got)}, gen-2 served both")
print("TRACE_SMOKE_OK")
PY
rc=$?
if [ "$rc" -ne 0 ]; then
    cat "$LOG" >&2
    echo "FAIL: trace smoke exited rc=$rc" >&2
    exit 1
fi
cat "$LOG"

grep -q "TRACE_SMOKE_OK" "$LOG" \
    || { echo "FAIL: no TRACE_SMOKE_OK attestation" >&2; exit 1; }
grep -q "0 negative spans, phases telescope to e2e" "$LOG" \
    || { echo "FAIL: no causal-ordering attestation" >&2; exit 1; }
grep -q "names every in-flight id" "$LOG" \
    || { echo "FAIL: no flight-dump attestation" >&2; exit 1; }

# the report tool must render the same dir without error
python tools/trace_report.py "$WORK/telemetry" --fail-on-negative \
    >/dev/null \
    || { echo "FAIL: trace_report.py choked on the smoke dir" >&2
         exit 1; }

echo "OK: distributed tracing — lifecycles assemble causally ordered" \
     "across router + prefill + decode, zero negative spans, and a" \
     "router kill leaves a flight dump naming every in-flight request"
