#!/usr/bin/env bash
# Router fault-tolerance smoke (ISSUE 18, CPU): run the bench's
# routerchaos phase — a journaled 1-prefill + 1-decode fleet under the
# supervised router, SIGKILL the router mid-traffic with in-flight and
# crossed-handoff work, relaunch against the same journal — and grep
# the attestations that make the control plane crash-safe:
#   - the fleet_router_recovery_s JSON metric line parses
#   - lost_requests == 0            (zero admitted requests lost)
#   - readopts == 2                 (both workers re-adopted, warm)
#   - replica_restarts == 0         (re-adoption, not restarts)
#   - "0 lost, token-exact" / "re-adopted (pids unchanged, 0 compiles)"
# BENCH_RC_OVERHEAD=0 skips the in-process overhead run (the full
# bench measures it); keeps this inside the 120s budget.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

WORK=$(mktemp -d "${TMPDIR:-/tmp}/paddle_tpu_rc_smoke.XXXXXX")
trap 'rm -rf "$WORK"' EXIT
LOG="$WORK/smoke.log"

timeout -k 10 120 env JAX_PLATFORMS=cpu \
    BENCH_FLEET_PHASES=routerchaos BENCH_RC_OVERHEAD=0 \
    python -u bench.py --fleet --cpu-mesh 1 >"$LOG" 2>&1
rc=$?
if [ "$rc" -ne 0 ]; then
    cat "$LOG" >&2
    echo "FAIL: routerchaos phase exited rc=$rc" >&2
    exit 1
fi
cat "$LOG"

grep -q '"metric": "fleet_router_recovery_s"' "$LOG" \
    || { echo "FAIL: no fleet_router_recovery_s metric line" >&2; exit 1; }
python - "$LOG" <<'PY' || exit 1
import json
import sys

rec = None
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if cand.get("metric") == "fleet_router_recovery_s":
            rec = cand
if rec is None:
    print("FAIL: metric line did not parse", file=sys.stderr)
    raise SystemExit(1)
assert rec["lost_requests"] == 0, rec
assert rec["readopts"] == 2, rec
assert rec["replica_restarts"] == 0, rec
assert rec["value"] >= 0, rec
assert rec["killed_at"]["pending"] >= 1, rec
assert rec["killed_at"]["kv_handoffs"] >= 1, rec
print(f"parsed: recovery {rec['value']}s, killed holding "
      f"{rec['killed_at']['pending']} in-flight "
      f"({rec['killed_at']['kv_handoffs']} handoffs), "
      f"{rec['readopts']} readopts, 0 restarts, 0 lost")
PY
grep -q "0 lost, token-exact" "$LOG" \
    || { echo "FAIL: no zero-lost/token-parity attestation" >&2; exit 1; }
grep -q "re-adopted (pids unchanged, 0 compiles)" "$LOG" \
    || { echo "FAIL: no re-adoption attestation" >&2; exit 1; }
echo "OK: router fault tolerance — SIGKILLed router relaunched from" \
     "its journal, workers re-adopted warm, zero requests lost," \
     "token-exact"
