#!/bin/bash
# Hunt for a live axon tunnel without wedging it further.
#
# The relay in this container supports ONE client at a time; a SIGKILLed
# client leaves it draining for many minutes (r5 observation: 10 min of
# quiet was not always enough).  So: one gentle probe per CYCLE seconds,
# SIGTERM-first kill, and on the first successful probe immediately run
# the full bench orchestrator (kernel-check gate + timed runs) with a
# generous envelope.  Exits after one successful bench, or when
# /tmp/stop_hunt exists.  Log: tools/bench_hunt.log
cd /root/repo || exit 1
LOG=tools/bench_hunt.log
CYCLE=${CYCLE:-1200}
# Hard deadline (epoch seconds): stop probing well before the round's
# driver runs its own bench — a SIGKILLed probe client leaves the relay
# draining, which would poison the driver's probes.
DEADLINE=${DEADLINE:-0}
touch "$LOG"
while true; do
  [ -f /tmp/stop_hunt ] && { echo "$(date -u +%FT%TZ) stop flag — exiting" >>"$LOG"; exit 0; }
  if [ "$DEADLINE" -gt 0 ] && [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "$(date -u +%FT%TZ) deadline reached — exiting" >>"$LOG"; exit 0
  fi
  echo "$(date -u +%FT%TZ) probe..." >>"$LOG"
  timeout -k 15 240 python -u bench.py --probe >>"$LOG" 2>&1
  prc=$?
  if [ "$prc" -eq 0 ]; then
    echo "$(date -u +%FT%TZ) PROBE OK — launching full bench" >>"$LOG"
    sleep 45    # let the probe client's session drain before the next client
    BENCH_BUDGET_S=${BENCH_BUDGET_S:-2400} BENCH_KC_BUDGET_S=700 \
    BENCH_PROBE_TIMEOUT_S=180 BENCH_PROBE_COOLDOWN_S=240 \
      python -u bench.py >>"$LOG" 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) bench rc=$rc" >>"$LOG"
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) bench SUCCEEDED — artifacts fresh" >>"$LOG"
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) probe failed/wedged (rc=$prc)" >>"$LOG"
  fi
  sleep "$CYCLE"
done
