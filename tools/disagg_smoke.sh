#!/usr/bin/env bash
# Prefill/decode disaggregation smoke (ISSUE 15, ~25s CPU): run the
# bench's disagg phase on a 1-prefill + 1-decode fleet (unified
# comparison leg skipped for budget) and grep the attestations that
# make the feature real:
#   - the fleet_disagg_decode_p99_s JSON metric line parses
#   - "lost_requests": 0                  (zero lost through handoffs)
#   - kv_handoffs > 0                     (pages really crossed)
#   - the decode-latency attestation line (loose CI bound; see below)
# Budget: 120s.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

WORK=$(mktemp -d "${TMPDIR:-/tmp}/paddle_tpu_disagg_smoke.XXXXXX")
trap 'rm -rf "$WORK"' EXIT
LOG="$WORK/smoke.log"

# Timing knobs, loosened for CI noise — NOT the bench contract:
#   PCTL=90    with 10 shorts the nearest-rank p99 IS the max; one
#              scheduler stall fails the ratio with no real leak.
#   RATIO=2.5  unchanged-tree runs on this 1-core box measured 1.19x
#              to 2.11x across one day (3 processes on 1 core — the
#              loaded wave is at the scheduler's mercy), so a tight
#              bound here only gates on host weather.  2.5x still
#              catches a catastrophic leak; the full bench phase keeps
#              the real PR-15 contract (p99 <= 1.3x) for benching.
# The smoke's sharp assertions are the MACHINERY ones below: handoffs
# crossed, zero lost, metric parses.
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    BENCH_FLEET_PHASES=disagg BENCH_DISAGG_UNIFIED=0 \
    BENCH_DISAGG_SHORT=10 BENCH_DISAGG_PACE_S=0.08 \
    BENCH_DISAGG_LONG_CONC=2 BENCH_DISAGG_PCTL=90 \
    BENCH_DISAGG_P99_RATIO=2.5 \
    python -u bench.py --fleet --cpu-mesh 1 >"$LOG" 2>&1
rc=$?
if [ "$rc" -ne 0 ]; then
    cat "$LOG" >&2
    echo "FAIL: disagg phase exited rc=$rc" >&2
    exit 1
fi
cat "$LOG"

grep -q '"metric": "fleet_disagg_decode_p99_s"' "$LOG" \
    || { echo "FAIL: no fleet_disagg_decode_p99_s metric line" >&2; exit 1; }
python - "$LOG" <<'PY' || exit 1
import json
import sys

rec = None
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if cand.get("metric") == "fleet_disagg_decode_p99_s":
            rec = cand
if rec is None:
    print("FAIL: metric line did not parse", file=sys.stderr)
    raise SystemExit(1)
assert rec["lost_requests"] == 0, rec
assert rec["kv_handoffs"] > 0, rec
assert rec["ratio_vs_quiet"] <= rec["ratio_bound"], rec
print(f"parsed: decode p99 {rec['value']}s "
      f"({rec['ratio_vs_quiet']}x quiet), "
      f"{rec['kv_handoffs']} handoffs, 0 lost")
PY
grep -q "0 lost" "$LOG" \
    || { echo "FAIL: no zero-lost attestation" >&2; exit 1; }
grep -q "kv handoffs" "$LOG" \
    || { echo "FAIL: no handoff attestation" >&2; exit 1; }
grep -Eq "decode p[0-9]+ [0-9]+ms quiet" "$LOG" \
    || { echo "FAIL: no decode-p99 attestation" >&2; exit 1; }
echo "OK: disaggregation — decode p99 flat under prefill pressure," \
     "KV pages handed off, zero lost"
