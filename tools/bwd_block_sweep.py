"""Sweep flash-attention backward block sizes on the live chip.
Usage: python tools/bwd_block_sweep.py  (prints one line per variant)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attn as fa

B, N, H, D = 4, 2048, 16, 128
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
do = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)


def fetch(xs):
    return float(sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in xs))


def timeit(fn, iters=20):
    fetch(fn(q, k, v, do))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v, do)
    fetch(out)
    return (time.perf_counter() - t0) / iters * 1e3


out, lse = jax.jit(lambda q, k, v: fa._flash_attention_tpu(
    q, k, v, True, return_lse=True))(q, k, v)
fetch([out])
print("lse ready", flush=True)

for bq, bk in [(128, 128), (256, 256), (512, 512), (256, 512), (512, 256)]:
    try:
        f = jax.jit(lambda q, k, v, do, bq=bq, bk=bk:
                    fa._flash_attention_bwd_tpu(q, k, v, out, lse, do, True,
                                                block_q=bq, block_k=bk))
        print(f"bwd bq={bq} bk={bk}: {timeit(f):.3f} ms", flush=True)
    except Exception as e:                                 # noqa: BLE001
        print(f"bwd bq={bq} bk={bk}: FAIL {type(e).__name__}: "
              f"{str(e)[:100]}", flush=True)

g = jax.jit(jax.grad(lambda q, k, v, do: jnp.vdot(
    fa._ref_attention(q, k, v, True).astype(jnp.float32),
    do.astype(jnp.float32)), argnums=(0, 1, 2)))
print(f"xla bwd: {timeit(lambda q, k, v, do: g(q, k, v, do)):.3f} ms",
      flush=True)
