#!/usr/bin/env python
"""Render assembled request traces from a telemetry directory.

Reads the ``trace`` events that a traced fleet run (``PADDLE_TRACE=1``)
wrote into the per-rank JSONL logs under ``PADDLE_TELEMETRY_DIR``,
stitches them into causally-ordered request lifecycles
(observability/aggregate.py: clock-skew-corrected across router and
replica processes), and prints the per-phase latency attribution
rollup — p50/p95/p99 in queue / prefill / parked / inject / decode /
ack, per priority class, with the owning role per phase.

Usage:
    python tools/trace_report.py <telemetry_dir> [--json]
        [--lifecycles N] [--chrome OUT.json] [--fail-on-negative]

``--chrome`` exports the lifecycles as a chrome-trace file (load in
chrome://tracing or Perfetto): one process row per role, one thread
row per request, complete events per phase and instants per hop.

Exit code 0 on success; pass --fail-on-negative to CI-gate on
negative spans (exit 2) — a negative span means clock correction
failed to keep causality, which the tier-1 bar forbids.
"""
import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_aggregate():
    """Load paddle_tpu/observability standalone — WITHOUT importing the
    paddle_tpu package (whose __init__ initializes XLA backends).  The
    observability modules are stdlib-only at import time by design, so
    this tool stays usable on a box whose TPU tunnel is wedged — the
    exact postmortem scenario it exists for."""
    pkg_dir = os.path.join(REPO, "paddle_tpu", "observability")
    name = "_ptpu_observability"
    if name in sys.modules:
        return sys.modules[name].aggregate
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod.aggregate


# the same boundary pairs _trace_phases telescopes over; spelled out
# here because chrome complete-events need the START of each span, not
# just its duration
_PHASE_BOUNDS = (
    ("queue", "admit", "dispatch"),
    ("prefill", "dispatch", "park"),
    ("parked", "park", "ship"),
    ("inject", "ship", "inject"),
    ("decode", "inject", "completion"),
    ("service", "dispatch", "completion"),
    ("ack", "completion", "ack"),
)


def _boundaries(lc):
    t = {}
    for ev in lc["events"]:
        name = ev.get("name")
        if name not in t:
            t[name] = ev.get("t_corrected", ev.get("t"))
    return t


def chrome_trace(lifecycles, phase_roles):
    """Lifecycles -> chrome-trace ``traceEvents`` list.  Rows: one
    process per role (router / prefill / decode / ...), one thread per
    request; each phase a complete ("X") event on the owning role's
    row, each hop an instant ("i") on the row of the process that
    emitted it."""
    out = []
    pids, tids = {}, {}
    t0 = min((lc["t0"] for lc in lifecycles), default=0.0)

    def _pid(role):
        role = role or "?"
        if role not in pids:
            pids[role] = len(pids) + 1
            out.append({"name": "process_name", "ph": "M",
                        "pid": pids[role], "tid": 0,
                        "args": {"name": role}})
        return pids[role]

    def _tid(pid, rid):
        key = (pid, rid)
        if key not in tids:
            tids[key] = len(tids) + 1
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tids[key], "args": {"name": rid}})
        return tids[key]

    for lc in lifecycles:
        rid = lc.get("request_id") or lc["trace_id"]
        bounds = _boundaries(lc)
        for phase, dur in (lc.get("phases") or {}).items():
            start = next((bounds[a] for p, a, b in _PHASE_BOUNDS
                          if p == phase and a in bounds), None)
            if start is None:
                continue
            pid = _pid(phase_roles.get(phase, "?"))
            out.append({"name": phase, "ph": "X", "cat": "phase",
                        "ts": round((start - t0) * 1e6, 1),
                        "dur": round(max(dur, 0.0) * 1e6, 1),
                        "pid": pid, "tid": _tid(pid, rid),
                        "args": {"trace_id": lc["trace_id"],
                                 "priority": lc.get("priority")}})
        for ev in lc["events"]:
            pid = _pid(ev.get("role"))
            t = ev.get("t_corrected", ev.get("t"))
            out.append({"name": ev["name"], "ph": "i", "cat": "hop",
                        "ts": round((t - t0) * 1e6, 1),
                        "pid": pid, "tid": _tid(pid, rid), "s": "t",
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("event", "name", "t",
                                              "t_corrected")}})
    return out


def _lifecycle_lines(lifecycles, limit):
    """The ``limit`` slowest lifecycles, one line each."""
    lines = []
    for lc in sorted(lifecycles, key=lambda x: -x["e2e_s"])[:limit]:
        phases = " ".join(f"{p}={v * 1e3:.1f}ms"
                          for p, v in lc["phases"].items())
        lines.append(
            f"  {lc.get('request_id') or lc['trace_id']:<20} "
            f"e2e={lc['e2e_s'] * 1e3:8.1f}ms  {phases}")
        lines.append(f"    hops: {' -> '.join(lc['hops'])}")
    return lines


def main(argv=None):
    parser = argparse.ArgumentParser("trace_report")
    parser.add_argument("telemetry_dir",
                        help="directory holding events_rank*.jsonl "
                             "written by a PADDLE_TRACE=1 run")
    parser.add_argument("--json", action="store_true",
                        help="emit the attribution rollup as JSON "
                             "instead of text")
    parser.add_argument("--lifecycles", type=int, default=0,
                        metavar="N",
                        help="also print the N slowest lifecycles "
                             "with their hop chains")
    parser.add_argument("--chrome", metavar="OUT.json",
                        help="write a chrome-trace export of every "
                             "lifecycle to OUT.json")
    parser.add_argument("--fail-on-negative", action="store_true",
                        help="exit 2 when any negative span survives "
                             "clock correction")
    args = parser.parse_args(argv)

    if not os.path.isdir(args.telemetry_dir):
        print(f"trace_report: no such directory: {args.telemetry_dir}",
              file=sys.stderr)
        return 1

    aggregate = _load_aggregate()
    events = aggregate.trace_events_from_dir(args.telemetry_dir)
    lifecycles = aggregate.assemble_traces(events=events)
    if not lifecycles:
        if events:
            print(f"trace_report: {len(events)} trace events under "
                  f"{args.telemetry_dir} but none carry a trace_id — "
                  f"nothing to assemble (ids are minted at submit "
                  f"time, so PADDLE_TRACE=1 must be set when requests "
                  f"enter, not only when they finish)", file=sys.stderr)
        else:
            print(f"trace_report: no trace events under "
                  f"{args.telemetry_dir} (was the run PADDLE_TRACE=1?)",
                  file=sys.stderr)
        return 1
    attr = aggregate.trace_attribution(lifecycles)

    if args.chrome:
        events = chrome_trace(lifecycles, aggregate.PHASE_ROLES)
        with open(args.chrome, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        print(f"# trace_report: wrote {len(events)} chrome-trace "
              f"events -> {args.chrome}", file=sys.stderr)

    if args.json:
        print(json.dumps(attr, indent=1, sort_keys=True))
    else:
        print(aggregate.format_trace_report(attr))
        if args.lifecycles > 0:
            print("\n".join(_lifecycle_lines(lifecycles,
                                             args.lifecycles)))

    if args.fail_on_negative and attr.get("negative_spans"):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
