"""Namespace-by-namespace API coverage vs the reference.

AST-reads each reference module's ``__all__`` (no reference import — it
needs the fluid C++ core) and hasattr-checks the same dotted path on
paddle_tpu.  The fluid.layers variant of this sweep lives in
fluid_coverage.py; this is the same method for every other user-facing
namespace.

Run: PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/api_coverage.py
Exit 0 when nothing is missing.
"""
import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF = "/root/reference/python/paddle"

# reference module (relative .py path) -> paddle_tpu dotted namespace
MODULES = {
    "__init__.py": "",
    "nn/__init__.py": "nn",
    "nn/functional/__init__.py": "nn.functional",
    "nn/initializer/__init__.py": "nn.initializer",
    "nn/utils/__init__.py": "nn.utils",
    "optimizer/__init__.py": "optimizer",
    "optimizer/lr.py": "optimizer.lr",
    "static/__init__.py": "static",
    "static/nn/__init__.py": "static.nn",
    "io/__init__.py": "io",
    "amp/__init__.py": "amp",
    "metric/__init__.py": "metric",
    "vision/__init__.py": "vision",
    "vision/ops.py": "vision.ops",
    "vision/transforms/__init__.py": "vision.transforms",
    "vision/datasets/__init__.py": "vision.datasets",
    "vision/models/__init__.py": "vision.models",
    "text/__init__.py": "text",
    "distributed/__init__.py": "distributed",
    "distributed/fleet/__init__.py": "distributed.fleet",
    "distributed/fleet/utils/__init__.py": "distributed.fleet.utils",
    "tensor/__init__.py": "tensor",
    "jit/__init__.py": "jit",
    "autograd/__init__.py": "autograd",
    "regularizer.py": "regularizer",
    "distribution.py": "distribution",
    "utils/__init__.py": "utils",
    "device/__init__.py": "device",
    "hub.py": "hub",
    "onnx/__init__.py": "onnx",
    "inference/__init__.py": "inference",
}


def ref_all(path):
    """Names in the module's ``__all__`` (assignments and += extends)."""
    full = os.path.join(REF, path)
    if not os.path.exists(full):
        return None
    names = []
    tree = ast.parse(open(full, encoding="utf-8").read())
    for node in ast.walk(tree):
        tgt = None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    tgt = node.value
        elif (isinstance(node, ast.AugAssign)
              and getattr(node.target, "id", "") == "__all__"):
            tgt = node.value
        if tgt is not None:
            try:
                names += list(ast.literal_eval(tgt))
            except (ValueError, SyntaxError):
                pass
    return names


def resolve(ns):
    import paddle_tpu
    obj = paddle_tpu
    for part in [p for p in ns.split(".") if p]:
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def main():
    total = have = 0
    report = []
    for path, ns in sorted(MODULES.items()):
        names = ref_all(path)
        if not names:
            continue
        names = sorted(set(names))
        obj = resolve(ns)
        missing = ([n for n in names if not hasattr(obj, n)]
                   if obj is not None else list(names))
        total += len(names)
        have += len(names) - len(missing)
        label = ns or "paddle"
        report.append((label, len(names) - len(missing), len(names),
                       missing))
    width = max(len(r[0]) for r in report)
    any_missing = False
    for label, h, t, missing in report:
        mark = "" if not missing else "   MISSING: " + ", ".join(missing)
        if missing:
            any_missing = True
        print(f"{label:<{width}}  {h}/{t}{mark}")
    print(f"\nTOTAL {have}/{total}")
    return 1 if any_missing else 0


if __name__ == "__main__":
    sys.exit(main())
