"""Reproducible fluid.layers coverage measurement (VERDICT r3 item 7).

Parses the reference's fluid/layers/*.py __all__ lists (no import — the
reference isn't runnable here), dedups, and hasattr-sweeps
paddle_tpu.fluid.layers.  Prints the measured count and the explicit
missing-name list; exits 0 always (a report, not a gate).

Run: PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/fluid_coverage.py
"""
import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF = "/root/reference/python/paddle/fluid/layers"


def ref_all_names():
    names = []
    for fn in sorted(os.listdir(REF)):
        if not fn.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(REF, fn)).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        try:
                            names += list(ast.literal_eval(node.value))
                        except ValueError:
                            pass
            elif isinstance(node, ast.AugAssign):
                if getattr(node.target, "id", None) == "__all__":
                    try:
                        names += list(ast.literal_eval(node.value))
                    except ValueError:
                        pass
    seen, out = set(), []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return out


def main():
    from paddle_tpu.fluid import layers
    names = ref_all_names()
    present = [n for n in names if hasattr(layers, n)]
    missing = [n for n in names if not hasattr(layers, n)]
    print(f"reference fluid.layers __all__ (deduped): {len(names)}")
    print(f"present in paddle_tpu.fluid.layers:      {len(present)}")
    print(f"missing ({len(missing)}):")
    for n in missing:
        print(f"  - {n}")


if __name__ == "__main__":
    main()
