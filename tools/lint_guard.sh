#!/usr/bin/env bash
# Full compile-hygiene static-analysis gate: every PTL rule over the
# package, the tools and the bench driver (<30s on the CPU container).
# A NEW finding (unsuppressed, unbaselined) fails the same way a dirty
# worktree fails tier-1 — tools/tier1_guard.sh runs this first.
#
# Rules: PTL001 moving-api, PTL002 tracer-leak, PTL003 donation safety,
# PTL004 host-sync-in-hot-path, PTL005 lock-order cycles, PTL000
# suppression hygiene.  See README "Static analysis".
#
# Usage: tools/lint_guard.sh [extra analyzer args...]
# Exit:  0 clean, 1 findings, 2 environment error.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

start=$(date +%s)
# ptl_lint.py = the same analyzer CLI standalone-loaded without the
# paddle_tpu package import, so the gate runs jax-less and in ~1s
python tools/ptl_lint.py paddle_tpu tools bench.py "$@"
rc=$?
elapsed=$(( $(date +%s) - start ))
if [ "$rc" -eq 1 ]; then
    echo "lint_guard: FAIL — new findings (${elapsed}s)" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "lint_guard: analyzer failed to run (exit $rc, ${elapsed}s)" >&2
    exit 2
fi
echo "lint_guard: OK (${elapsed}s)"
