#!/usr/bin/env bash
# AOT cold-start smoke (ISSUE 14, ~15s): warm an artifact dir with a
# small paged engine, then boot a FRESH replica process from it and
# grep the attestations that make the feature real:
#   - "aot_cold_boot_compiles=0"   (zero XLA backend compiles)
#   - "aot_token_parity=OK"        (bitwise-identical greedy tokens)
#   - "aot_ttft_s=..."             (time-to-first-token of the cold boot)
# Budget: 60s.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

WORK=$(mktemp -d "${TMPDIR:-/tmp}/paddle_tpu_aot_smoke.XXXXXX")
trap 'rm -rf "$WORK"' EXIT
LOG="$WORK/smoke.log"

run_boot() {
    # $1 = mode (seed|load)
    timeout -k 5 60 env JAX_PLATFORMS=cpu \
        PADDLE_AOT_CACHE_DIR="$WORK/aot" PADDLE_JIT_CACHE_DIR="$WORK/jit" \
        python - "$1" "$WORK" <<'PY'
import json
import os
import sys
import time

t0 = time.perf_counter()
import numpy as np
from jax import monitoring

events = []
monitoring.register_event_duration_secs_listener(
    lambda e, d, **kw: events.append(e) if "backend_compile" in e else None)

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.serving import PagedServingEngine

mode, work = sys.argv[1], sys.argv[2]
cfg = G.gpt_tiny()
if mode == "seed":
    import jax
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    G.save_params_npz(os.path.join(work, "params.npz"), params)
else:
    params = G.load_params_npz(os.path.join(work, "params.npz"))
eng = PagedServingEngine((params, cfg), slots=2, max_len=64,
                         seq_buckets=[16, 32], batch_buckets=[1, 2],
                         page_size=8)
eng.warmup()
rng = np.random.RandomState(5)
prompts = [rng.randint(1, 512, n) for n in (5, 9, 20)]
req = eng.submit(prompts[0], 8)
while not req.done:
    eng.step()
ttft = time.perf_counter() - t0
toks = [req.tokens] + eng.generate(prompts[1:], max_new_tokens=8)
st = eng.stats()
ref_path = os.path.join(work, "ref_tokens.json")
if mode == "seed":
    with open(ref_path, "w") as f:
        json.dump(toks, f)
    parity = "SEEDED"
else:
    with open(ref_path) as f:
        parity = "OK" if json.load(f) == toks else "MISMATCH"
print(f"aot_mode={mode} decode_compiles={st['decode_compiles']}")
print(f"aot_cold_boot_compiles={len(events)}")
print(f"aot_token_parity={parity}")
print(f"aot_ttft_s={ttft:.3f}")
PY
}

echo "# aot_smoke: seeding artifact dir (full compile)" >&2
run_boot seed >"$LOG" 2>&1 || { cat "$LOG" >&2; echo "FAIL: seed boot" >&2; exit 1; }
grep -q "aot_token_parity=SEEDED" "$LOG" || { cat "$LOG" >&2; exit 1; }
ls "$WORK/aot"/*.aotx >/dev/null 2>&1 \
    || { echo "FAIL: no artifacts serialized" >&2; exit 1; }

echo "# aot_smoke: fresh replica from artifacts" >&2
run_boot load >"$LOG" 2>&1 || { cat "$LOG" >&2; echo "FAIL: cold boot" >&2; exit 1; }
cat "$LOG"
grep -q "aot_cold_boot_compiles=0" "$LOG" \
    || { echo "FAIL: artifact-warm replica compiled" >&2; exit 1; }
grep -q "aot_token_parity=OK" "$LOG" \
    || { echo "FAIL: token parity broke across the artifact boot" >&2; exit 1; }
grep -q "decode_compiles=1" "$LOG" \
    || { echo "FAIL: decode_compiles != 1" >&2; exit 1; }
grep -Eq "aot_ttft_s=[0-9.]+" "$LOG" \
    || { echo "FAIL: no TTFT attestation" >&2; exit 1; }
echo "OK: aot cold start — 0 XLA compiles, token-exact, TTFT attested"
