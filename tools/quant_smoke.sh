#!/usr/bin/env bash
# Quantized-serving smoke: int8 weight-only executables + int8 paged KV
# through the PagedServingEngine on CPU, inside a hard 60s budget — CI's
# proof that the quantized serving path (ISSUE 9) still works end to
# end: dequant matmuls in every executable, quantize-on-write pages,
# dequantize-on-read attention, quantized prefix reuse.
#
# Asserts: (1) the int8 engine boots and serves every request;
# (2) decode_compiles == 1 and the measured wave issues ZERO new XLA
# compiles; (3) the prefix cache recorded >= 1 hit on QUANTIZED pages
# (the repeated system prompt re-acquired int8+scale page pairs);
# (4) greedy tokens match the fp32 paged engine exactly and max logit
# error stays inside the declared budget; (5) the quant counters moved
# (quant_matmuls, kv_quant_bytes_saved); (6) the JSONL telemetry parses
# and holds serving_step records.
#
# Usage: tools/quant_smoke.sh
set -o pipefail
cd "$(dirname "$0")/.." || exit 2
REPO=$(pwd)

TDIR=$(mktemp -d /tmp/quant_smoke.XXXXXX)
trap 'rm -rf "$TDIR"' EXIT
mkdir -p "$TDIR/telemetry"

run_py() {
    timeout -k 5 55 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
        PADDLE_TELEMETRY_DIR="$TDIR/telemetry" python "$@"
}

run_py - <<'PY' || { echo "quant_smoke: FAIL (engine)" >&2; exit 1; }
import numpy as np
import jax
from paddle_tpu.models import gpt as G
from paddle_tpu.inference.serving import PagedServingEngine
from paddle_tpu.observability import metrics

cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                  num_heads=2, max_seq_len=64, dtype="float32",
                  use_flash=False, remat=False)
params = G.init_params(cfg, jax.random.PRNGKey(0))

def make(**kw):
    return PagedServingEngine((params, cfg), slots=4, max_len=32,
                              page_size=4, seq_buckets=(8, 16),
                              batch_buckets=(1, 2), prefill_chunk=8,
                              capture_logits=True, **kw)

fp = make()                                       # the fp32 reference
eng = make(quant="int8", kv_dtype="int8")         # the quantized path
fp.warmup()
eng.warmup()
compiles0 = metrics.counter("compile.count").value

rng = np.random.RandomState(0)
sys_prompt = np.arange(1, 10).astype(np.int32)    # the shared system prompt
trace = []
for i in range(18):
    if i % 3 == 0:
        trace.append((sys_prompt, 4))             # repeated prefix -> hits
    else:
        trace.append((rng.randint(1, 256, rng.randint(3, 15))
                      .astype(np.int32), int(rng.randint(3, 9))))
trace.append((rng.randint(1, 256, 20).astype(np.int32), 4))  # chunked
freqs = [fp.submit(p, m) for p, m in trace]
fp.run()
qreqs = [eng.submit(p, m) for p, m in trace]
done = eng.run()
st = eng.stats()
new_compiles = metrics.counter("compile.count").value - compiles0
assert len(done) == len(trace), len(done)
assert st["decode_compiles"] == 1, st
assert new_compiles == 0, f"quant steady state retraced: {new_compiles}"
assert st["prefix_page_hits"] >= 1, st            # quantized pages re-shared
assert st["quant"] == "int8" and st["kv_dtype"] == "int8"
assert st["quant_matmuls"] > 0, st
assert st["kv_quant_bytes_saved"] > 0, st
assert st["pages_in_use"] == 0, st                # nothing leaked
budget = 0.05
max_err = 0.0
for a, b in zip(freqs, qreqs):
    assert a.tokens == b.tokens, (b.id, a.tokens, b.tokens)
    for la, lb in zip(a.logits, b.logits):
        max_err = max(max_err, float(np.abs(la - lb).max()))
assert max_err <= budget, (max_err, budget)
print(f"# quant_smoke: {len(trace)} requests ok, greedy==fp32, "
      f"logit_err={max_err:.2e}<=budget {budget}, "
      f"prefix_hits={st['prefix_page_hits']}, "
      f"quant_matmuls={st['quant_matmuls']}, "
      f"kv_saved={st['kv_quant_bytes_saved']}, "
      f"steady_compiles={new_compiles}, decode_compiles=1")
PY

# every JSONL line must parse; serving_step records must be present
run_py - <<PY || { echo "quant_smoke: FAIL (jsonl)" >&2; exit 1; }
import glob, json
steps = 0
files = glob.glob("$TDIR/telemetry/events_rank*.jsonl")
assert files, "no event log written"
for path in files:
    for line in open(path):
        rec = json.loads(line)
        if rec.get("event") == "serving_step":
            steps += 1
assert steps > 5, f"expected serving_step records, found {steps}"
print("# jsonl parses:", steps, "serving steps")
PY

echo "quant_smoke: OK"
