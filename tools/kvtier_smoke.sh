#!/usr/bin/env bash
# Fleet-scale KV smoke (ISSUE 17, ~30s CPU): run the bench's kvtier
# phase — 2 unified replicas with tight device pools + a host-RAM page
# tier vs a single giant replica on identical shared-prefix traffic —
# and grep the attestations that make the feature real:
#   - the fleet_prefix_hit_rate JSON metric line parses
#   - ratio_vs_giant <= ratio_bound    (sticky routing keeps hit-rate)
#   - pages_spilled >= 1               (device pages really spilled)
#   - fault_backs >= 1, rejects == 0   (hash-verified fault-backs,
#                                       no re-prefill, no bad KV)
#   - "sticky routing held" / "spilled to the host tier" /
#     "hash-verified fault-backs" / "zero steady-state compiles"
# Budget: 120s.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

WORK=$(mktemp -d "${TMPDIR:-/tmp}/paddle_tpu_kvtier_smoke.XXXXXX")
trap 'rm -rf "$WORK"' EXIT
LOG="$WORK/smoke.log"

timeout -k 10 120 env JAX_PLATFORMS=cpu \
    BENCH_FLEET_PHASES=kvtier \
    python -u bench.py --fleet --cpu-mesh 1 >"$LOG" 2>&1
rc=$?
if [ "$rc" -ne 0 ]; then
    cat "$LOG" >&2
    echo "FAIL: kvtier phase exited rc=$rc" >&2
    exit 1
fi
cat "$LOG"

grep -q '"metric": "fleet_prefix_hit_rate"' "$LOG" \
    || { echo "FAIL: no fleet_prefix_hit_rate metric line" >&2; exit 1; }
python - "$LOG" <<'PY' || exit 1
import json
import sys

rec = None
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if cand.get("metric") == "fleet_prefix_hit_rate":
            rec = cand
if rec is None:
    print("FAIL: metric line did not parse", file=sys.stderr)
    raise SystemExit(1)
assert rec["ratio_vs_giant"] <= rec["ratio_bound"], rec
assert rec["pages_spilled"] >= 1, rec
assert rec["fault_backs"] >= 1, rec
assert rec["pages_faulted_back"] >= 1, rec
assert rec["fault_back_rejects"] == 0, rec
assert rec["prefix_routed"] >= 1, rec
assert rec["lost_requests"] == 0, rec
print(f"parsed: hit-rate {rec['value']} "
      f"({rec['ratio_vs_giant']}x giant, bound {rec['ratio_bound']}x), "
      f"{rec['pages_spilled']} spilled, {rec['fault_backs']} "
      f"fault-backs, 0 rejects, 0 lost")
PY
grep -q "sticky routing held" "$LOG" \
    || { echo "FAIL: no sticky-routing attestation" >&2; exit 1; }
grep -q "pages spilled to the host tier" "$LOG" \
    || { echo "FAIL: no spill attestation" >&2; exit 1; }
grep -q "hash-verified fault-backs" "$LOG" \
    || { echo "FAIL: no fault-back attestation" >&2; exit 1; }
grep -q "zero steady-state compiles per replica" "$LOG" \
    || { echo "FAIL: no steady-compile attestation" >&2; exit 1; }
echo "OK: fleet-scale KV — sticky routing held prefix hit-rate," \
     "pages spilled to host and hash-verified back, zero re-prefills," \
     "zero steady-state compiles"
