#!/usr/bin/env bash
# Fleet smoke: the multi-replica serving kill-and-recover scenario on the
# CPU backend, inside a hard 120s budget — CI's proof that the serving
# fleet (router + supervised engine replicas + re-queueing + warm
# restarts) still survives a replica SIGKILL end to end.
#
# Runs bench.py --fleet (--cpu-mesh 2 re-execs with a clean forced-CPU
# env, same dance as tests/conftest.py): 2 replicas take ~20 requests of
# sustained traffic, one replica is SIGKILLed while it provably holds
# in-flight requests, and the bench asserts zero lost requests,
# token-exact parity of the re-queued requests vs an uninterrupted run,
# and a replacement replica that warm-restarts from the shared
# persistent compilation cache.  This script additionally greps the
# parsed JSON metric line for fleet_recovery_time_s and the
# warm-restart compile count being exactly 0.
#
# Usage: tools/fleet_smoke.sh
# Exit:  bench exit status, or 1 if the metric line / warm-restart
#        assertion is missing.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

LOG=$(mktemp /tmp/fleet_smoke.XXXXXX.log)
# chaos phase only: the autoscale phase has its own smoke + budget
# (tools/autoscale_smoke.sh)
timeout -k 10 120 env JAX_PLATFORMS=cpu BENCH_FLEET_REQUESTS=20 \
    BENCH_FLEET_PHASES=chaos \
    python bench.py --fleet --cpu-mesh 2 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

if [ "$rc" -ne 0 ]; then
    echo "fleet_smoke: FAIL (rc=$rc)" >&2
    exit "$rc"
fi
if ! grep -q '"metric": "fleet_recovery_time_s"' "$LOG"; then
    echo "fleet_smoke: FAIL — fleet ran but emitted no parsed" \
         "fleet_recovery_time_s metric line" >&2
    exit 1
fi
if ! grep -q '"lost_requests": 0' "$LOG"; then
    echo "fleet_smoke: FAIL — metric line does not attest zero lost" \
         "requests" >&2
    exit 1
fi
if ! grep -q '"warm_cache_misses": 0' "$LOG"; then
    echo "fleet_smoke: FAIL — replacement replica did not warm-restart" \
         "with 0 persistent-cache misses" >&2
    exit 1
fi
echo "fleet_smoke: OK"
