#!/usr/bin/env python
"""Render a cross-rank telemetry report from a telemetry directory.

Reads the per-rank JSONL event logs (``events_rank<R>.jsonl``) and
published snapshots (``snapshot_rank<R>.json``) that a training run wrote
under ``PADDLE_TELEMETRY_DIR`` (or that ``launch.py --telemetry`` pointed
workers at), merges them (observability/aggregate.py), and prints the
group-wide view: per-rank step counts and step-time mean/p50/p95, XLA
compile counts, collective-wait totals, step skew, straggler flags and
per-rank fault counters.

Usage:
    python tools/telemetry_report.py <telemetry_dir> [--json]
        [--straggler-gap SECONDS] [--step-lag N]

Exit code 0 on success (stragglers flagged in the report do NOT fail the
tool; pass --fail-on-straggler to CI-gate on them).
"""
import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_aggregate():
    """Load paddle_tpu/observability standalone — WITHOUT importing the
    paddle_tpu package (whose __init__ initializes XLA backends).  The
    observability modules are stdlib-only at import time by design, so
    this tool stays usable on a box whose TPU tunnel is wedged — the
    exact postmortem scenario it exists for."""
    pkg_dir = os.path.join(REPO, "paddle_tpu", "observability")
    name = "_ptpu_observability"
    if name in sys.modules:
        return sys.modules[name].aggregate
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod.aggregate


def main(argv=None):
    parser = argparse.ArgumentParser("telemetry_report")
    parser.add_argument("telemetry_dir",
                        help="directory holding events_rank*.jsonl / "
                             "snapshot_rank*.json")
    parser.add_argument("--json", action="store_true",
                        help="emit the merged report as JSON instead of "
                             "text")
    parser.add_argument("--straggler-gap", type=float, default=None,
                        help="collective-wait asymmetry threshold in "
                             "seconds (default: "
                             "PADDLE_TELEMETRY_STRAGGLER or 0.2)")
    parser.add_argument("--step-lag", type=int, default=None,
                        help="steps behind the group frontier before a "
                             "rank is flagged (default: "
                             "PADDLE_TELEMETRY_STEP_LAG or 2)")
    parser.add_argument("--fail-on-straggler", action="store_true",
                        help="exit 2 when any straggler is flagged")
    parser.add_argument("--traces", action="store_true",
                        help="append the distributed-trace summary "
                             "(lifecycles, negative spans, dominant "
                             "phase, flight dumps) from the same dir")
    args = parser.parse_args(argv)

    if not os.path.isdir(args.telemetry_dir):
        print(f"telemetry_report: no such directory: "
              f"{args.telemetry_dir}", file=sys.stderr)
        return 1

    aggregate = _load_aggregate()

    report = aggregate.merge_from_dir(
        args.telemetry_dir, straggler_gap_s=args.straggler_gap,
        step_lag=args.step_lag)
    if args.traces:
        report["traces"] = aggregate.trace_summary(args.telemetry_dir)
    if not report["nranks_seen"] and not (
            args.traces and report["traces"]["trace_events"]):
        # a serving-only dir has no step/snapshot records; with
        # --traces it is still a renderable artifact
        print(f"telemetry_report: no events_rank*.jsonl or "
              f"snapshot_rank*.json under {args.telemetry_dir}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(aggregate.format_report(report))
        if args.traces:
            t = report["traces"]
            print(f"traces: {t['traces']} lifecycles / "
                  f"{t['trace_events']} events, "
                  f"negative spans: {t['negative_spans']}, "
                  f"dominant phase: {t['dominant_phase'] or '-'}, "
                  f"flight dumps: {t['flight_dumps']}"
                  + ("" if t["traces"] else
                     "  (none assembled; trace with PADDLE_TRACE=1)"))
    if args.fail_on_straggler and report["stragglers"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
