#!/usr/bin/env bash
# Paged-KV serving smoke: mixed-length + shared-prefix + chunked traffic
# through the PagedServingEngine on CPU, inside a hard 60s budget — CI's
# proof that the block-table pager, the paged decode step, the prefix
# cache and the chunked-prefill interleave still work end to end.
#
# Asserts: (1) every request completes with the requested token counts;
# (2) decode_compiles == 1 and the measured wave issues ZERO new XLA
# compiles (warmup covers ladder + chunk + COW executables); (3) the
# prefix cache recorded >= 1 page hit (the repeated system prompt
# re-acquired physical pages); (4) the JSONL telemetry parses line by
# line and holds serving_step records carrying pages_in_use.
#
# Usage: tools/paged_smoke.sh
set -o pipefail
cd "$(dirname "$0")/.." || exit 2
REPO=$(pwd)

TDIR=$(mktemp -d /tmp/paged_smoke.XXXXXX)
trap 'rm -rf "$TDIR"' EXIT
mkdir -p "$TDIR/telemetry"

# same env scrub as testing/env.clean_cpu_env: forced CPU backend, the
# container's sitecustomize dropped from PYTHONPATH
run_py() {
    timeout -k 5 55 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
        PADDLE_TELEMETRY_DIR="$TDIR/telemetry" python "$@"
}

run_py - <<'PY' || { echo "paged_smoke: FAIL (engine)" >&2; exit 1; }
import numpy as np
import jax
from paddle_tpu.models import gpt as G
from paddle_tpu.inference.serving import PagedServingEngine
from paddle_tpu.observability import metrics

cfg = G.GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                  num_heads=2, max_seq_len=64, dtype="float32",
                  use_flash=False, remat=False)
params = G.init_params(cfg, jax.random.PRNGKey(0))
eng = PagedServingEngine((params, cfg), slots=4, max_len=32, page_size=4,
                         seq_buckets=(8, 16), batch_buckets=(1, 2),
                         prefill_chunk=8)
eng.warmup()
compiles0 = metrics.counter("compile.count").value

rng = np.random.RandomState(0)
sys_prompt = np.arange(1, 10).astype(np.int32)    # the shared system prompt
reqs = []
for i in range(24):
    if i % 3 == 0:
        p = sys_prompt                            # repeated prefix -> hits
    else:
        p = rng.randint(1, 256, rng.randint(3, 15)).astype(np.int32)
    reqs.append(eng.submit(p, int(rng.randint(3, 9))))
reqs.append(eng.submit(rng.randint(1, 256, 20).astype(np.int32), 4))  # chunked
done = eng.run()
st = eng.stats()
new_compiles = metrics.counter("compile.count").value - compiles0
assert len(done) == 25, len(done)
for r in reqs:
    assert r.done and len(r.tokens) == r.max_new_tokens \
        or r.finish_reason == "eos", (r.id, r.tokens)
assert st["decode_compiles"] == 1, st
assert new_compiles == 0, f"steady state retraced: {new_compiles} compiles"
assert st["prefix_page_hits"] >= 1, st            # shared prompt really hit
assert st["prefill_chunks"] >= 2, st              # the long prompt chunked
assert st["pages_in_use"] == 0, st                # nothing leaked
print(f"# paged_smoke: 25 requests ok, prefix_hits={st['prefix_page_hits']}, "
      f"chunks={st['prefill_chunks']}, cow={st['cow_copies']}, "
      f"steady_compiles={new_compiles}, decode_compiles=1")
PY

# every JSONL line must parse; serving_step records carry pages_in_use
run_py - <<PY || { echo "paged_smoke: FAIL (jsonl)" >&2; exit 1; }
import glob, json
steps = paged = 0
files = glob.glob("$TDIR/telemetry/events_rank*.jsonl")
assert files, "no event log written"
for path in files:
    for line in open(path):
        rec = json.loads(line)
        if rec.get("event") == "serving_step":
            steps += 1
            paged += "pages_in_use" in rec
assert steps > 5, f"expected serving_step records, found {steps}"
assert paged == steps, f"{steps - paged} steps missing pages_in_use"
print("# jsonl parses:", steps, "paged serving steps")
PY

echo "paged_smoke: OK"
