"""ERNIE-3.0-Base / BERT-base pretrain throughput on one chip
(BASELINE.json's headline metric names ERNIE tokens/sec/chip).

Prints one JSON line like bench.py; timed region ends with a host fetch
(block_until_ready does not sync through the remote-exec layer here).
Run: python tools/bench_bert.py [--model ernie|bert] [--batch N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="ernie", choices=["ernie", "bert"])
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    from bench import _peak_flops, TARGET_MFU, _arm_watchdog
    from paddle_tpu.models import bert

    _arm_watchdog()
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    cfg = (bert.ernie_3_base() if args.model == "ernie"
           else bert.bert_base()) if on_tpu else bert.bert_tiny()
    batch = args.batch or (64 if on_tpu else 4)
    steps = args.steps if on_tpu else 2
    N = cfg.max_seq_len if hasattr(cfg, "max_seq_len") else 512

    params, m, v = bert.init_pretrain_state(cfg, jax.random.PRNGKey(0))
    step = bert.make_train_step(cfg)

    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, N)),
                       jnp.int32)
    # 15% masked-LM positions, rest ignored (-100)
    mask = rng.rand(batch, N) < 0.15
    mlm = jnp.asarray(np.where(mask, np.asarray(toks), -100), jnp.int32)
    nsp = jnp.asarray(rng.randint(0, 2, (batch,)), jnp.int32)
    lr = jnp.float32(1e-4)

    params, m, v, loss = step(params, m, v, jnp.int32(1), toks, mlm, nsp,
                              lr)
    float(loss)                      # compile + warm (host fetch)

    t0 = time.perf_counter()
    for i in range(steps):
        params, m, v, loss = step(params, m, v, jnp.int32(i + 2), toks,
                                  mlm, nsp, lr)
    final_loss = float(loss)         # host fetch closes the region
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = batch * N * steps / dt
    mfu = tokens_per_sec * cfg.flops_per_token() / _peak_flops(dev)
    assert 0.0 < mfu <= 1.0 or not on_tpu, mfu
    print(json.dumps({
        "metric": f"{args.model}_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / TARGET_MFU, 4),
    }))
    print(f"# model={args.model} params={cfg.num_params()/1e6:.0f}M "
          f"seq={N} batch={batch} loss={final_loss:.4f} mfu={mfu:.3f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
