#!/usr/bin/env bash
# Chaos smoke: the single-host kill-and-recover scenario on the CPU
# backend, inside a hard 120s budget — CI's proof that the supervised
# launcher + async checkpointing + fault registry still recover a
# training run end to end.
#
# Runs bench.py --faults (--cpu-mesh 4 re-execs with a clean forced-CPU
# env, same dance as tests/conftest.py): a 2-process DP group has rank 1
# killed mid-step by a PADDLE_FAULTS spec, the supervisor relaunches the
# group, workers resume from the last published checkpoint, and final
# params must match an uninterrupted run to 1e-6.  The parsed JSON
# metric line (fault_recovery_time_s) is asserted present.
#
# Usage: tools/chaos_smoke.sh
# Exit:  bench exit status, or 1 if no metric line was emitted.
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

LOG=$(mktemp /tmp/chaos_smoke.XXXXXX.log)
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python bench.py --faults --cpu-mesh 4 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL (rc=$rc)" >&2
    exit "$rc"
fi
if ! grep -q '"metric": "fault_recovery_time_s"' "$LOG"; then
    echo "chaos_smoke: FAIL — recovery ran but emitted no parsed" \
         "fault_recovery_time_s metric line" >&2
    exit 1
fi
echo "chaos_smoke: OK"
